"""Continuous batching: iteration-level scheduling over the slot cache.

The Orca insight, host-side: the scheduler's unit of work is one decode
ITERATION, not one request. Every iteration it (1) admits arrived
requests into free slots (prefill runs as its own compiled program —
prefill/decode disaggregation — and splices straight into the slot),
(2) runs ONE decode step for every live slot, and (3) evicts the slots
that finished. Requests join and leave mid-flight; the compiled decode
program never notices, because admission and eviction are counter
updates plus a dynamic_update_slice splice (inference/kv_cache.py).

The arrival process is OPEN-LOOP: requests carry absolute arrival
offsets and join the queue when the wall clock passes them, whether or
not the engine has capacity — so TTFT honestly includes queue wait, and
offered load above capacity shows up as a growing queue, not as a
throttled arrival rate (the closed-loop benchmarking mistake).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request in the open-loop stream."""
    rid: int
    prompt: np.ndarray                  # [P] int32 token ids
    max_new_tokens: int = 16
    arrival_s: float = 0.0              # offset from serve() start
    # -- runtime state (scheduler-owned) --
    slot: Optional[int] = None
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    t_arrival: float = 0.0              # absolute clock
    t_admit: Optional[float] = None     # slot acquired (queue wait end)
    t_first: Optional[float] = None     # first token produced (TTFT end)
    t_last: Optional[float] = None      # latest token produced
    admission_attempts: int = 0         # head-of-queue rejections

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None \
            else self.t_first - self.t_arrival

    @property
    def queue_wait_s(self) -> Optional[float]:
        """Arrival → admission: the router/scheduler backlog share of
        TTFT (the part more replicas would fix)."""
        return None if self.t_admit is None \
            else self.t_admit - self.t_arrival

    @property
    def service_ttft_s(self) -> Optional[float]:
        """Admission → first token: the prefill share of TTFT (the part
        a faster prefill would fix)."""
        if self.t_admit is None or self.t_first is None:
            return None
        return self.t_first - self.t_admit

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first (the streaming
        cadence a user sees); None for single-token responses."""
        if self.t_first is None or self.t_last is None \
                or len(self.out_tokens) < 2:
            return None
        return (self.t_last - self.t_first) / (len(self.out_tokens) - 1)


def synthetic_requests(n: int, prompt_len: Tuple[int, int] = (8, 16),
                       max_new_tokens: int = 16, rate_rps: float = 0.0,
                       vocab_size: int = 512, seed: int = 0
                       ) -> List[Request]:
    """An open-loop synthetic arrival stream: ``rate_rps`` > 0 draws
    exponential inter-arrival gaps (Poisson arrivals at that rate);
    rate 0 = everything arrives at t=0 (the saturation stream the
    occupancy acceptance gate uses). Prompts are uniform random tokens
    with lengths in ``prompt_len`` (inclusive)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    lo, hi = prompt_len
    for i in range(n):
        if rate_rps > 0 and i > 0:
            t += float(rng.exponential(1.0 / rate_rps))
        plen = int(rng.integers(lo, hi + 1))
        prompt = rng.integers(0, vocab_size, size=plen).astype(np.int32)
        out.append(Request(rid=i, prompt=prompt,
                           max_new_tokens=max_new_tokens, arrival_s=t))
    return out


def shared_prefix_requests(n: int, prefix_len: int = 32,
                           tail_len: Tuple[int, int] = (4, 12),
                           max_new_tokens: int = 16,
                           rate_rps: float = 0.0, vocab_size: int = 512,
                           seed: int = 0) -> List[Request]:
    """The shared-prefix open-loop workload: every request carries the
    SAME ``prefix_len``-token system prompt followed by a random tail
    in ``tail_len`` (inclusive) — the traffic shape prefix-shared
    paging is built for (common system prompts / few-shot preambles,
    varying user turns). Arrival process as in
    ``synthetic_requests``."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, vocab_size, size=prefix_len).astype(np.int32)
    t = 0.0
    out = []
    lo, hi = tail_len
    for i in range(n):
        if rate_rps > 0 and i > 0:
            t += float(rng.exponential(1.0 / rate_rps))
        tail = rng.integers(0, vocab_size,
                            size=int(rng.integers(lo, hi + 1))
                            ).astype(np.int32)
        out.append(Request(rid=i,
                           prompt=np.concatenate([prefix, tail]),
                           max_new_tokens=max_new_tokens, arrival_s=t))
    return out


class ContinuousBatchingScheduler:
    """Per-iteration insert/evict over an InferenceEngine's slots."""

    def __init__(self, engine, temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 idle_sleep_s: float = 0.0005,
                 max_wall_s: Optional[float] = None,
                 trace=None):
        self.engine = engine
        self.temperature = float(temperature)
        self.eos_token = eos_token
        self.idle_sleep_s = float(idle_sleep_s)
        self.max_wall_s = max_wall_s
        # Request-scoped span recorder (monitor/request_trace.py): the
        # router passes a shared one so a request's route decision and
        # its replica-side spans land in the same record; standalone
        # serves build their own when telemetry is on. Pure host state —
        # zero added device syncs either way.
        if trace is None and getattr(engine.telemetry, "enabled", False):
            from ..monitor.request_trace import RequestTrace
            trace = RequestTrace()
        self.trace = trace

    # ------------------------------------------------------------------ #
    def _finished(self, req: Request, slot_len: int) -> bool:
        if len(req.out_tokens) >= req.max_new_tokens:
            return True
        if self.eos_token is not None and req.out_tokens and \
                req.out_tokens[-1] == self.eos_token:
            return True
        # Slot full: the next decode would have nowhere to write.
        return slot_len >= self.engine.max_len

    def _complete(self, req: Request) -> None:
        self.engine.complete_request(
            req.rid, req.ttft_s or 0.0, req.tpot_s,
            prompt_tokens=len(req.prompt),
            new_tokens=len(req.out_tokens),
            queue_wait_s=req.queue_wait_s,
            service_ttft_s=req.service_ttft_s,
            admission_attempts=req.admission_attempts)
        if self.trace is not None:
            self.trace.complete(req.rid, t=req.t_last,
                                telemetry=self.engine.telemetry)

    def _reject(self, req: Request, queue_len: int) -> None:
        """Head-of-queue admission rejection: per-request attempt count,
        aggregator total, first-rejection event, trace mark."""
        eng = self.engine
        req.admission_attempts += 1
        reason = getattr(eng, "last_admit_block", None) or "no_slot"
        if self.trace is not None:
            self.trace.admit_reject(req.rid, reason=reason)
        note = getattr(eng, "note_admission_reject", None)
        if note is not None:
            note(req.rid, reason, req.admission_attempts, queue_len)

    def _admit_trace(self, req: Request, slot: int) -> None:
        if self.trace is None:
            return
        eng = self.engine
        self.trace.admit(req.rid, slot, t=req.t_admit,
                         replica=getattr(eng, "replica", "") or None)
        info_fn = getattr(eng, "last_admit_info", None)
        info = info_fn(slot) if info_fn is not None else {}
        self.trace.prefill(req.rid, (req.t_first or req.t_admit)
                           - req.t_admit, tokens=len(req.prompt),
                           chunks=info.get("chunks", 1),
                           cached_tokens=info.get("cached_tokens", 0),
                           cow_fork=info.get("cow_fork", False))
        self.trace.first_token(req.rid, t=req.t_first)

    # ------------------------------------------------------------------ #
    def serve(self, requests: Sequence[Request]) -> Dict[str, Any]:
        """Run the stream to completion; returns the serving report
        (the aggregator snapshot + per-request records)."""
        eng = self.engine
        t0 = time.perf_counter()
        trace = self.trace
        ledger = getattr(eng.serving, "ledger", None)
        pending = deque(sorted(requests, key=lambda r: r.arrival_s))
        queue: deque = deque()
        active: Dict[int, Request] = {}
        # Engines with a block pool own slot selection (prefix-affinity
        # group choice + HBM admission gate); the scheduler keeps its
        # own free list for engines that predate it (slot-major, test
        # fakes) — slot occupancy is then the whole gate.
        select = getattr(eng, "select_slot", None)
        free: deque = deque(() if select else
                            (i for i in range(eng.max_slots)
                             if not eng.active[i]))
        # Speculative decoding emits 1..k+1 tokens per slot per
        # iteration; greedy only — exact rejection sampling for
        # temperature > 0 is not implemented, so sampling streams fall
        # back to plain decode.
        spec = bool(getattr(eng, "spec_enabled", False)) and \
            self.temperature == 0.0

        def _release(slot):
            eng.release_slot(slot)
            if not select:
                free.append(slot)

        while pending or queue or active:
            now = time.perf_counter() - t0
            if self.max_wall_s is not None and now > self.max_wall_s:
                # Abandon the run WITHOUT leaking capacity: mid-flight
                # slots must come back, or the engine's next serve()
                # starts with no free slots and spins forever.
                abort = getattr(eng, "abort_request", None)
                t_ab = time.perf_counter()
                for slot in list(active):
                    req = active[slot]
                    if trace is not None:
                        trace.abort(req.rid, "max_wall", t=t_ab,
                                    telemetry=eng.telemetry)
                    if abort is not None:
                        abort(req.rid, "max_wall")
                    _release(slot)
                    del active[slot]
                for req in queue:
                    # Enqueued but never admitted: starved, not served —
                    # counts against SLO availability like any abort.
                    if trace is not None:
                        trace.abort(req.rid, "starved", t=t_ab,
                                    telemetry=eng.telemetry)
                    if abort is not None:
                        abort(req.rid, "starved")
                break
            # 1. open-loop arrivals join the queue on schedule.
            while pending and pending[0].arrival_s <= now:
                req = pending.popleft()
                req.t_arrival = t0 + req.arrival_s
                if trace is not None:
                    trace.enqueue(req.rid, t=req.t_arrival)
                queue.append(req)
            # 2. admissions: prefill into free slots. FCFS — when the
            # head of the queue cannot be admitted (no slot, or the
            # block pool cannot cover its worst case), everything
            # behind it waits; pool exhaustion rejects admission here
            # and NEVER touches a live slot. Paged engines admit in
            # one-slot-per-group BATCHES (engine.prefill_many): a full
            # batch prefills G admissions for one admission's wall.
            batched = select is not None and \
                getattr(eng, "paged", False) and eng.prefill_chunk > 0
            while queue:
                if batched:
                    batch = []
                    used: set = set()
                    while queue:
                        req = queue[0]
                        slot = select(req.prompt, req.max_new_tokens,
                                      exclude_groups=used)
                        if slot is None:
                            # Only a rejection with NO exclusions is the
                            # gate refusing the head (with exclusions it
                            # may just be this batch's one-slot-per-group
                            # shape).
                            if not used:
                                self._reject(req, len(queue))
                            break
                        queue.popleft()
                        req.t_admit = time.perf_counter()
                        used.add(eng.group_of(slot))
                        batch.append((req, slot))
                    if not batch:
                        break
                    with eng.telemetry.span(
                            "prefill", slots=len(batch),
                            tokens=sum(len(r.prompt)
                                       for r, _ in batch)):
                        results = eng.prefill_many(
                            [(slot, req.prompt, req.max_new_tokens)
                             for req, slot in batch], self.temperature)
                    t_now = time.perf_counter()
                    for (req, slot), (tok, _) in zip(batch, results):
                        req.slot = slot
                        req.t_first = req.t_last = t_now
                        req.out_tokens = [tok]
                        eng.activate_slot(slot, len(req.prompt), tok)
                        eng.serving.note_prefill(len(req.prompt))
                        self._admit_trace(req, slot)
                        if self._finished(req, eng.context_len(slot)):
                            self._complete(req)
                            _release(slot)
                        else:
                            active[slot] = req
                    continue
                req = queue[0]
                if select is not None:
                    slot = select(req.prompt, req.max_new_tokens)
                    if slot is None:
                        self._reject(req, len(queue))
                        break
                elif free:
                    slot = free.popleft()
                else:
                    break
                queue.popleft()
                req.t_admit = time.perf_counter()
                with eng.telemetry.span("prefill", slot=slot,
                                        tokens=len(req.prompt)):
                    tok, _ = eng.prefill(
                        req.prompt, slot, self.temperature,
                        max_new_tokens=req.max_new_tokens)
                req.slot = slot
                req.t_first = req.t_last = time.perf_counter()
                req.out_tokens = [tok]
                eng.activate_slot(slot, len(req.prompt), tok)
                eng.serving.note_prefill(len(req.prompt))
                self._admit_trace(req, slot)
                if self._finished(req, eng.context_len(slot)):
                    self._complete(req)
                    _release(slot)
                else:
                    active[slot] = req
            # 3. one decode (or draft-then-verify) iteration for every
            # live slot.
            if active and spec:
                emitted, n_new = eng.spec_decode_once(self.temperature)
                t_now = time.perf_counter()
                occ = len(active)
                for slot in list(active):
                    req = active[slot]
                    budget = req.max_new_tokens - len(req.out_tokens)
                    n = int(n_new[slot])
                    toks = [int(t) for t in emitted[slot, :n]]
                    if self.eos_token is not None and \
                            self.eos_token in toks:
                        toks = toks[:toks.index(self.eos_token) + 1]
                    req.out_tokens.extend(toks[:max(budget, 0)])
                    req.t_last = t_now
                    if trace is not None:
                        trace.tick(req.rid, occ, n, t=t_now,
                                   proposed=eng.spec_k,
                                   accepted=max(n - 1, 0))
                    if self._finished(req, eng.context_len(slot)):
                        self._complete(req)
                        _release(slot)
                        del active[slot]
            elif active:
                sampled, _ = eng.decode_once(self.temperature)
                t_now = time.perf_counter()
                occ = len(active)
                for slot in list(active):
                    req = active[slot]
                    req.out_tokens.append(int(sampled[slot]))
                    req.t_last = t_now
                    if trace is not None:
                        trace.tick(req.rid, occ, 1, t=t_now)
                    if self._finished(req, eng.context_len(slot)):
                        self._complete(req)
                        _release(slot)
                        del active[slot]
            elif pending and not queue:
                # Idle ahead of the next arrival — open-loop wait. The
                # watchdog heartbeat says "idle, not hung": a sparse
                # arrival stream must not read as a decode-loop stall.
                eng.telemetry.heartbeat()
                gap = pending[0].arrival_s - (time.perf_counter() - t0)
                if gap > 0:
                    t_sl = time.perf_counter()
                    time.sleep(min(gap, self.idle_sleep_s))
                    if ledger is not None:
                        ledger.note("idle", time.perf_counter() - t_sl)
            elif queue:
                # Queued work but no free slot and nothing decoding:
                # capacity is held outside this serve (caller-activated
                # slots). Yield instead of busy-spinning — unless
                # nothing can EVER free the capacity the head request
                # needs (an over-sized request on an idle engine), which
                # must fail loudly, not hang.
                if select is not None and not active and not pending \
                        and not eng.active.any():
                    req = queue[0]
                    raise RuntimeError(
                        f"request {req.rid} can never be admitted: "
                        f"{len(req.prompt)} prompt + "
                        f"{req.max_new_tokens} new tokens exceeds the "
                        "block pool's per-group capacity")
                eng.telemetry.heartbeat()
                t_sl = time.perf_counter()
                time.sleep(self.idle_sleep_s)
                if ledger is not None:
                    ledger.note("admission_blocked",
                                time.perf_counter() - t_sl)

        wall = time.perf_counter() - t0
        # Final drain with a SERVE-WALL-anchored snapshot: a run shorter
        # than report_steps iterations would otherwise never put the
        # aggregator snapshot (tokens/s, decode-step percentiles) into
        # any report record, and telemetry_report's serving section
        # would carry nulls; the last report record wins there, so this
        # also pins the figure benches compare to the same wall
        # SERVE_BENCH.json uses.
        if eng.telemetry.enabled:
            eng.telemetry.drain({"serving": eng.serving.snapshot(
                wall_s=wall)})
        report = dict(eng.serving.snapshot(wall_s=wall))
        report["recompiles"] = eng.telemetry.recompile_count
        report["unfinished"] = len(pending) + len(queue) + len(active)
        if trace is not None:
            report["trace"] = trace.summary()
        report["requests"] = [
            {"rid": r.rid, "prompt_tokens": len(r.prompt),
             "new_tokens": len(r.out_tokens),
             "ttft_ms": round(r.ttft_s * 1e3, 3)
             if r.ttft_s is not None else None,
             "tpot_ms": round(r.tpot_s * 1e3, 3)
             if r.tpot_s is not None else None,
             "tokens": list(map(int, r.out_tokens))}
            for r in sorted(requests, key=lambda r: r.rid)]
        return report


__all__ = ["Request", "synthetic_requests", "shared_prefix_requests",
           "ContinuousBatchingScheduler"]
