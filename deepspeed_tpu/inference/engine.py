"""InferenceEngine — the serving-tier counterpart of the training
engine: checkpoint/params in, continuously-batched tokens out.

Architecture (mirrors the training engine's discipline):

- TWO compiled programs serve everything: ``decode_step`` (one token for
  every slot at once) and ``prefill_step`` (one chunk of one slot's
  prompt — or the whole padded prompt when ``prefill_chunk: 0``). Both
  have fixed abstract signatures for the lifetime of the engine and both
  are wrapped by the recompile sentinel; ``fail_on_recompile`` turns any
  post-warmup retrace into a hard error. Request admission, progress,
  and eviction never touch a compiled shape.
- The KV cache (inference/kv_cache.py) is born sharded: slots over the
  mesh data axis, heads over the model axis. Its buffers are DONATED
  through every step, so the cache exists once.
- Host-side per-slot counters (lengths, active, last token) are the
  scheduler's state; they enter each step as tiny int arrays. The one
  device fetch per decode iteration is the sampled-token readback — the
  inherent serving sync (the host must see tokens to detect EOS and
  feed the next step), and it is the ONLY one.
- Telemetry rides the training spine unchanged: per-iteration step
  records (occupancy, active slots, fenced step wall), ``prefill``
  spans, ``request_complete`` events, and the ``ServingAggregator``
  snapshot (TTFT/TPOT p50/p95, tokens/s) in every drain's report
  record. ``tools/telemetry_report.py`` turns the stream into the
  ``serving`` section benches and CI diff.
- Weight quantization (``inference.quantize``): bf16 via the stochastic
  -rounding machinery, or int8-at-rest with in-step dequantize
  (inference/quantize.py).
"""
from __future__ import annotations

import os
import time
import weakref
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import decode as decode_mod
from . import kv_cache
from .quantize import dequantize, quantize_params, quantized_bytes
from .. import constants as C
from ..models.gpt2 import GPT2Config
from ..monitor import Telemetry
from ..monitor.memory import analytic_state_bytes
from ..monitor.serving import ServingAggregator
from ..parallel.topology import build_mesh, DP_AXIS, MP_AXIS, SP_AXIS
from ..runtime.config import InferenceConfig, TelemetryConfig
from ..runtime.config_utils import load_config_json
from ..utils.logging import log_dist

try:
    from flax import serialization as flax_serialization
except Exception:  # pragma: no cover
    flax_serialization = None


class InferenceEngine:
    """Batched autoregressive serving over a device mesh."""

    def __init__(self, model_cfg: GPT2Config, params: Any,
                 config: Any = None, mesh: Optional[Mesh] = None,
                 rng: Optional[jax.Array] = None,
                 param_shardings: Any = None):
        if isinstance(config, str):
            config = load_config_json(config)
        config = dict(config or {})
        self.model_cfg = model_cfg
        self.icfg = InferenceConfig(config)
        self.tcfg = TelemetryConfig(config)
        self.mesh = mesh if mesh is not None else build_mesh()
        self.dp = int(self.mesh.shape.get(DP_AXIS, 1))
        self.mp = int(self.mesh.shape.get(MP_AXIS, 1))
        self.sp = int(self.mesh.shape.get(SP_AXIS, 1))

        # --- static serving geometry (all of it compiled-program shape) ---
        self.max_slots = int(self.icfg.max_slots)
        self.max_len = int(self.icfg.max_seq_len) or \
            int(model_cfg.max_seq_length)
        if self.max_len > model_cfg.max_seq_length:
            raise ValueError(
                f"inference.max_seq_len={self.max_len} exceeds the model's "
                f"position table ({model_cfg.max_seq_length})")
        self.prefill_chunk = int(self.icfg.prefill_chunk)
        if self.prefill_chunk > 0 and self.max_len % self.prefill_chunk:
            raise ValueError(
                f"inference.prefill_chunk={self.prefill_chunk} must divide "
                f"the cache capacity ({self.max_len}) — padded prompts "
                "would otherwise overrun the slot")
        if self.prefill_chunk == 0 and self.sp > 1 \
                and self.max_len % self.sp:
            raise ValueError(
                f"whole-prompt prefill with a seq axis needs max_seq_len "
                f"({self.max_len}) divisible by sp={self.sp}")

        # --- weights: quantize, then commit to the mesh ---
        self.quantize = self.icfg.quantize
        self._base_rng = rng if rng is not None else jax.random.PRNGKey(17)
        if self.quantize != "none" and param_shardings is not None:
            raise NotImplementedError(
                "inference.quantize does not compose with tensor-parallel "
                "param_shardings yet (quantized leaves change the tree "
                "structure the specs address)")
        params = quantize_params(params, self.quantize, self._base_rng)
        if param_shardings is not None:
            shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                param_shardings)
        else:
            shardings = NamedSharding(self.mesh, P())
        self._params = jax.device_put(params, shardings)
        self.param_bytes = quantized_bytes(self._params)

        # --- the KV cache, born sharded ---
        self.cache_spec = kv_cache.KVCacheSpec(
            num_layers=model_cfg.num_layers, num_slots=self.max_slots,
            num_heads=model_cfg.num_heads, max_len=self.max_len,
            head_dim=model_cfg.head_dim, dtype=model_cfg.dtype)
        self.cache = kv_cache.init_cache(self.cache_spec, self.mesh)
        self._cache_sh = kv_cache.cache_shardings(self.mesh)

        # --- host-authoritative per-slot counters ---
        self.lengths = np.zeros(self.max_slots, np.int32)
        self.active = np.zeros(self.max_slots, bool)
        self.last_tokens = np.zeros(self.max_slots, np.int32)

        # --- telemetry on the shared spine ---
        self.iterations = 0
        self._rng_calls = 0
        self.serving = ServingAggregator(self.max_slots)
        self.telemetry = Telemetry(
            self.tcfg, default_report_steps=50,
            meta=dict(mode="serving", model=model_cfg.name,
                      dp=self.dp, mp=self.mp, sp=self.sp,
                      max_slots=self.max_slots, max_seq_len=self.max_len,
                      prefill_chunk=self.prefill_chunk,
                      quantize=self.quantize,
                      precision=jnp.dtype(model_cfg.dtype).name,
                      param_bytes=self.param_bytes,
                      kv_cache_bytes=self.cache_spec.nbytes()))
        _ref = weakref.ref(self)
        self.telemetry.step_provider = lambda: (
            _ref().iterations if _ref() is not None else -1)
        self.telemetry.set_analytic_footprint(analytic_state_bytes(
            {"params": self._params, "cache": self.cache}))

        # --- the two compiled paths (sentinel-instrumented) ---
        self._decode_fn = self.telemetry.instrument_step_fn(
            "decode_step", self._build_decode_step())
        self._prefill_fn = self.telemetry.instrument_step_fn(
            "prefill_step", self._build_prefill_step())

        log_dist(
            f"InferenceEngine initialized: {model_cfg.name}, "
            f"slots={self.max_slots} (dp={self.dp}), "
            f"cache={self.max_len}x{model_cfg.num_heads}h "
            f"({self.cache_spec.nbytes() / 2 ** 20:.1f} MiB K+V), "
            f"prefill={'full' if self.prefill_chunk == 0 else f'chunk {self.prefill_chunk}'}, "
            f"quantize={self.quantize}", ranks=[0])

    # ------------------------------------------------------------------ #
    # Compiled-path builders
    # ------------------------------------------------------------------ #
    def _runtime_params(self, params):
        """Dequantize inside the compiled program (int8 at rest,
        compute-dtype transients); identity for none/bf16."""
        if self.quantize == "int8":
            return dequantize(params, self.model_cfg.dtype)
        return params

    def _build_decode_step(self) -> Callable:
        cfg = self.model_cfg

        def decode_step(params, kc, vc, tokens, lengths, key, temperature):
            p = self._runtime_params(params)
            logits, kc, vc = decode_mod.gpt2_decode(p, kc, vc, tokens,
                                                    lengths, cfg)
            sampled = decode_mod.sample_tokens(logits, key, temperature)
            return kc, vc, sampled, logits

        sh = self._cache_sh
        return jax.jit(decode_step, donate_argnums=(1, 2),
                       out_shardings=(sh["k"], sh["v"], None, None))

    def _build_prefill_step(self) -> Callable:
        cfg = self.model_cfg
        attention_fn = None
        if self.prefill_chunk == 0 and self.sp > 1:
            from ..ops.ring_attention import ring_attention_fn
            attention_fn = ring_attention_fn(self.mesh)

        def prefill_step(params, kc, vc, tokens, slot, start, last_idx,
                         key, temperature):
            p = self._runtime_params(params)
            if self.prefill_chunk == 0:
                logits, kc, vc = decode_mod.gpt2_prefill_full(
                    p, kc, vc, tokens, slot, last_idx, cfg,
                    attention_fn=attention_fn)
            else:
                logits, kc, vc = decode_mod.gpt2_prefill_chunk(
                    p, kc, vc, tokens, slot, start, last_idx, cfg)
            sampled = decode_mod.sample_tokens(logits, key, temperature)
            return kc, vc, sampled, logits

        sh = self._cache_sh
        return jax.jit(prefill_step, donate_argnums=(1, 2),
                       out_shardings=(sh["k"], sh["v"], None, None))

    def _next_key(self) -> jax.Array:
        self._rng_calls += 1
        return jax.random.fold_in(self._base_rng, self._rng_calls)

    # ------------------------------------------------------------------ #
    # Slot lifecycle (host counters only — no device work)
    # ------------------------------------------------------------------ #
    def activate_slot(self, slot: int, context_len: int,
                      last_token: int) -> None:
        """Mark a freshly prefilled slot live: the cache holds positions
        0..context_len-1 and ``last_token`` decodes at position
        context_len next step."""
        self.lengths[slot] = int(context_len)
        self.active[slot] = True
        self.last_tokens[slot] = int(last_token)

    def release_slot(self, slot: int) -> None:
        """Evict: counters clear; the stale cache rows are dead by
        masking and get overwritten by the next occupant."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_tokens[slot] = 0

    def context_len(self, slot: int) -> int:
        return int(self.lengths[slot])

    @property
    def active_slots(self) -> int:
        return int(self.active.sum())

    # ------------------------------------------------------------------ #
    # The two serving operations
    # ------------------------------------------------------------------ #
    def prefill(self, prompt: Sequence[int], slot: int,
                temperature: float = 0.0, return_logits: bool = False
                ) -> Tuple[int, Optional[np.ndarray]]:
        """Prefill one prompt into ``slot`` and sample its first output
        token. Returns (token, final-position logits [V] when asked —
        parity tests only; the serving loop needs just the token, and a
        per-admission [V] fetch would be a wasted host transfer). The
        caller activates the slot (scheduler owns admission ordering)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ValueError("empty prompt")
        if plen >= self.max_len:
            raise ValueError(
                f"prompt length {plen} leaves no room to generate in a "
                f"{self.max_len}-token slot")
        kc, vc = self.cache["k"], self.cache["v"]
        temp = np.float32(temperature)
        if self.prefill_chunk == 0:
            padded = np.zeros(self.max_len, np.int32)
            padded[:plen] = prompt
            kc, vc, tok, logits = self._prefill_fn(
                self._params, kc, vc, padded, np.int32(slot),
                np.int32(0), np.int32(plen - 1), self._next_key(), temp)
        else:
            chunk = self.prefill_chunk
            n_chunks = -(-plen // chunk)
            padded = np.zeros(n_chunks * chunk, np.int32)
            padded[:plen] = prompt
            tok = logits = None
            for ci in range(n_chunks):
                start = ci * chunk
                last = ci == n_chunks - 1
                last_idx = (plen - 1 - start) if last else 0
                kc, vc, tok, logits = self._prefill_fn(
                    self._params, kc, vc, padded[start:start + chunk],
                    np.int32(slot), np.int32(start), np.int32(last_idx),
                    self._next_key(), temp)
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        out_logits = np.asarray(jax.device_get(logits)) \
            if return_logits else None
        return int(jax.device_get(tok)), out_logits

    def decode_once(self, temperature: float = 0.0,
                    return_logits: bool = False
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """One decode iteration for every slot (inactive slots compute
        too — a uniform program is what keeps the signature fixed; their
        counters just don't advance). Returns the sampled token per slot
        (and the [S, V] logits when asked — tests only; the extra fetch
        is not part of the serving loop)."""
        t0 = time.perf_counter()
        n_active = self.active_slots
        kc, vc, sampled, logits = self._decode_fn(
            self._params, self.cache["k"], self.cache["v"],
            self.last_tokens, self.lengths, self._next_key(),
            np.float32(temperature))
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        # THE serving sync: the host needs the tokens (EOS detection +
        # next step's inputs). One batched [S] fetch per iteration.
        sampled = np.asarray(jax.device_get(sampled))
        adv = self.active
        self.lengths[adv] += 1
        self.last_tokens[adv] = sampled[adv]
        wall = time.perf_counter() - t0
        self.iterations += 1
        self.serving.note_iteration(n_active, wall)
        tl = self.telemetry
        if tl.enabled:
            tl.record_step(self.iterations, {},
                           wall_ms=wall * 1e3,
                           active_slots=n_active,
                           occupancy=round(n_active / self.max_slots, 4),
                           tokens=n_active)
            tl.maybe_drain(self.iterations, extra_fn=self._report_extra)
        out_logits = np.asarray(jax.device_get(logits)) \
            if return_logits else None
        return sampled, out_logits

    def _report_extra(self) -> Dict[str, Any]:
        return {"serving": self.serving.snapshot()}

    def complete_request(self, rid: Any, ttft_s: float,
                         tpot_s: Optional[float], prompt_tokens: int,
                         new_tokens: int) -> None:
        """Per-request goodput accounting at completion (host clocks
        only): feeds the aggregator and writes a ``request_complete``
        telemetry event."""
        self.serving.note_request(ttft_s, tpot_s, new_tokens)
        if self.telemetry.enabled:
            payload = {"rid": rid, "ttft_ms": round(ttft_s * 1e3, 3),
                       "prompt_tokens": int(prompt_tokens),
                       "new_tokens": int(new_tokens)}
            if tpot_s is not None:
                payload["tpot_ms"] = round(tpot_s * 1e3, 3)
            self.telemetry.event("request_complete", payload)

    def serve(self, requests, temperature: float = 0.0, **kwargs):
        """Drive a request list/stream through the continuous-batching
        scheduler; see inference/scheduler.py."""
        from .scheduler import ContinuousBatchingScheduler
        sched = ContinuousBatchingScheduler(self, temperature=temperature,
                                            **kwargs)
        return sched.serve(requests)

    # ------------------------------------------------------------------ #
    # Training-checkpoint handoff
    # ------------------------------------------------------------------ #
    @classmethod
    def from_train_checkpoint(cls, load_dir: str, model_cfg: GPT2Config,
                              config: Any = None, tag: Optional[str] = None,
                              mesh: Optional[Mesh] = None,
                              rng: Optional[jax.Array] = None,
                              init_fn: Optional[Callable] = None
                              ) -> "InferenceEngine":
        """Build a serving engine from a training engine's checkpoint
        directory (the ``latest``-pointer + ``mp_rank_00`` layout
        runtime/engine.py saves). ``init_fn(rng, cfg) -> params``
        defaults to ``models.gpt2.gpt2_init`` and is only used for its
        tree STRUCTURE (eval_shape — no real init runs)."""
        if flax_serialization is None:
            raise RuntimeError("flax is required to read checkpoints")
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.isfile(latest):
                raise FileNotFoundError(f"no 'latest' pointer in {load_dir}")
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        model_file = os.path.join(path, "mp_rank_00_model_states.msgpack")
        if not os.path.isfile(model_file):
            raise NotImplementedError(
                f"{model_file} not found — TP-sharded (mp_rank_XX) "
                "checkpoints need assembly, load them through the "
                "training engine and pass raw params instead")
        if init_fn is None:
            from ..models.gpt2 import gpt2_init
            init_fn = gpt2_init
        template = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda r: init_fn(r, model_cfg),
                           jax.random.PRNGKey(0)))
        with open(model_file, "rb") as f:
            blob = flax_serialization.from_bytes({"module": template},
                                                 f.read())
        log_dist(f"serving from training checkpoint {path}", ranks=[0])
        return cls(model_cfg, blob["module"], config=config, mesh=mesh,
                   rng=rng)

    # ------------------------------------------------------------------ #
    # Static lint audit (analysis/) — duck-typed lint_engine contract
    # ------------------------------------------------------------------ #
    def _lint_path_meta(self, name: str) -> Dict[str, Any]:
        """Pass metadata for the serving paths: no gradient sync exists
        here, so collective_placement is inert; materialization scales
        from the PER-DEVICE params+cache footprint (matching the
        post-partitioning shapes in the compiled HLO), with the largest
        per-device leaf exempt as usual."""
        state = {"params": self._params, "cache": self.cache}
        per_dev_leaves = []
        for leaf in jax.tree_util.tree_leaves(state):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and hasattr(sharding, "shard_shape"):
                try:
                    shape = sharding.shard_shape(tuple(shape))
                except Exception:
                    pass
            per_dev_leaves.append(
                int(np.prod(shape)) * jnp.dtype(leaf.dtype).itemsize)
        return {
            "grad_sync_path": False,
            "grad_sync_mode": "none",
            "gas": 1,
            "scatterable_leaf_bytes": [],
            "declared_state_bytes": int(analytic_state_bytes(state)),
            "param_bytes_full": int(self.param_bytes),
            "largest_leaf_bytes": max(per_dev_leaves, default=0),
            "dp": self.dp,
            "zero_stage": 0,
        }

    def lint_audit(self, config=None, waivers=None, passes=None):
        """Compile-time lint over the decode/prefill paths (host-side
        AOT re-lower from the sentinel registry; zero device fences).
        The serving contract: host_sync and materialization clean — no
        full-cache gather, no in-step host transfer."""
        from ..analysis.auditor import lint_engine
        return lint_engine(self, config=config, waivers=waivers,
                           passes=passes)

    def close(self) -> None:
        self.telemetry.close()


__all__ = ["InferenceEngine"]
