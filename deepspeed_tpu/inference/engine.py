"""InferenceEngine — the serving-tier counterpart of the training
engine: checkpoint/params in, continuously-batched tokens out.

Architecture (mirrors the training engine's discipline):

- TWO compiled programs serve everything: ``decode_step`` (one token for
  every slot at once) and ``prefill_step`` (one chunk of one slot's
  prompt — or the whole padded prompt when ``prefill_chunk: 0``). Both
  have fixed abstract signatures for the lifetime of the engine and both
  are wrapped by the recompile sentinel; ``fail_on_recompile`` turns any
  post-warmup retrace into a hard error. Request admission, progress,
  and eviction never touch a compiled shape.
- The KV cache (inference/kv_cache.py) is born sharded: slots over the
  mesh data axis, heads over the model axis. Its buffers are DONATED
  through every step, so the cache exists once.
- Host-side per-slot counters (lengths, active, last token) are the
  scheduler's state; they enter each step as tiny int arrays. The one
  device fetch per decode iteration is the sampled-token readback — the
  inherent serving sync (the host must see tokens to detect EOS and
  feed the next step), and it is the ONLY one.
- Telemetry rides the training spine unchanged: per-iteration step
  records (occupancy, active slots, fenced step wall), ``prefill``
  spans, ``request_complete`` events, and the ``ServingAggregator``
  snapshot (TTFT/TPOT p50/p95, tokens/s) in every drain's report
  record. ``tools/telemetry_report.py`` turns the stream into the
  ``serving`` section benches and CI diff.
- Weight quantization (``inference.quantize``): bf16 via the stochastic
  -rounding machinery, or int8-at-rest with in-step dequantize
  (inference/quantize.py).
"""
from __future__ import annotations

import os
import time
import weakref
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import decode as decode_mod
from . import kv_cache
from .quantize import (dequantize, quantize_params, quantized_bytes,
                       resolve_kv_dtype)
from .spec import NGramDrafter
from .. import constants as C
from ..models.gpt2 import GPT2Config
from ..monitor import Telemetry
from ..monitor.memory import analytic_state_bytes
from ..monitor.serving import ServingAggregator
from ..monitor.serving_slo import ServingGoodputLedger, SLOTracker
from ..ops import paged_attention as paged_attn_ops
from ..parallel.topology import build_mesh, DP_AXIS, MP_AXIS, SP_AXIS
from ..runtime.config import InferenceConfig, TelemetryConfig
from ..runtime.config_utils import load_config_json
from ..utils.logging import log_dist

try:
    from flax import serialization as flax_serialization
except Exception:  # pragma: no cover
    flax_serialization = None


class InferenceEngine:
    """Batched autoregressive serving over a device mesh."""

    def __init__(self, model_cfg: GPT2Config, params: Any,
                 config: Any = None, mesh: Optional[Mesh] = None,
                 rng: Optional[jax.Array] = None,
                 param_shardings: Any = None):
        if isinstance(config, str):
            config = load_config_json(config)
        config = dict(config or {})
        self.model_cfg = model_cfg
        self.icfg = InferenceConfig(config)
        self.tcfg = TelemetryConfig(config)
        self.mesh = mesh if mesh is not None else build_mesh()
        self.dp = int(self.mesh.shape.get(DP_AXIS, 1))
        self.mp = int(self.mesh.shape.get(MP_AXIS, 1))
        self.sp = int(self.mesh.shape.get(SP_AXIS, 1))

        # --- static serving geometry (all of it compiled-program shape) ---
        self.max_slots = int(self.icfg.max_slots)
        self.max_len = int(self.icfg.max_seq_len) or \
            int(model_cfg.max_seq_length)
        if self.max_len > model_cfg.max_seq_length:
            raise ValueError(
                f"inference.max_seq_len={self.max_len} exceeds the model's "
                f"position table ({model_cfg.max_seq_length})")
        self.prefill_chunk = int(self.icfg.prefill_chunk)
        if self.prefill_chunk > 0 and self.max_len % self.prefill_chunk:
            raise ValueError(
                f"inference.prefill_chunk={self.prefill_chunk} must divide "
                f"the cache capacity ({self.max_len}) — padded prompts "
                "would otherwise overrun the slot")
        if self.prefill_chunk == 0 and self.sp > 1 \
                and self.max_len % self.sp:
            raise ValueError(
                f"whole-prompt prefill with a seq axis needs max_seq_len "
                f"({self.max_len}) divisible by sp={self.sp}")
        self.block_size = int(self.icfg.block_size)
        self.paged = self.block_size > 0
        if self.paged and self.max_len % self.block_size:
            raise ValueError(
                f"inference.block_size={self.block_size} must divide "
                f"inference.max_seq_len ({self.max_len}); set "
                "block_size: 0 for the slot-major layout")
        self.spec_k = int(self.icfg.spec_k)
        self.replica = str(self.icfg.replica)
        self.num_blocks = int(self.icfg.num_blocks)
        if self.paged and self.num_blocks == 0:
            # Full provisioning: every slot can reach max_len, so
            # admission never blocks on HBM (the PR-7-equivalent
            # capacity); smaller pools oversubscribe and the admission
            # gate accounts free blocks.
            self.num_blocks = self.max_slots * \
                (self.max_len // self.block_size)
        if self.paged and self.num_blocks % self.dp:
            raise ValueError(
                f"inference.num_blocks={self.num_blocks} must be "
                f"divisible by the mesh data axis ({self.dp}) — blocks "
                "are born sharded over dp alongside their slots")
        # Pallas paged-attention kernel vs the one-hot pool contraction.
        # Resolved ONCE here: the compiled paths bake the choice in, so
        # flipping the env var mid-flight cannot desync the sentinel.
        self.paged_kernel = bool(
            self.paged and paged_attn_ops.paged_kernel_enabled(
                self.icfg.paged_kernel))

        # --- weights: quantize, then commit to the mesh ---
        self.quantize = self.icfg.quantize
        self._base_rng = rng if rng is not None else jax.random.PRNGKey(17)
        if self.quantize != "none" and param_shardings is not None:
            raise NotImplementedError(
                "inference.quantize does not compose with tensor-parallel "
                "param_shardings yet (quantized leaves change the tree "
                "structure the specs address)")
        params = quantize_params(params, self.quantize, self._base_rng)
        if param_shardings is not None:
            shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                param_shardings)
        else:
            shardings = NamedSharding(self.mesh, P())
        self._params = jax.device_put(params, shardings)
        self.param_bytes = quantized_bytes(self._params)

        # --- the KV cache, born sharded: paged block pool (production)
        # or the PR-7 slot-major rows (block_size: 0 — the parity
        # baseline) ---
        kv_dtype = resolve_kv_dtype(self.icfg.kv_cache_dtype,
                                    model_cfg.dtype)
        if self.paged:
            self.cache_spec = kv_cache.PagedKVCacheSpec(
                num_layers=model_cfg.num_layers,
                num_slots=self.max_slots, num_blocks=self.num_blocks,
                block_size=self.block_size, max_len=self.max_len,
                num_heads=model_cfg.num_heads,
                head_dim=model_cfg.head_dim, num_groups=self.dp,
                dtype=kv_dtype)
            self.cache = kv_cache.init_paged_cache(self.cache_spec,
                                                   self.mesh)
            self._cache_sh = kv_cache.paged_shardings(self.mesh)
            self.allocator = kv_cache.BlockAllocator(self.cache_spec)
            self.block_tables = np.full(
                (self.max_slots, self.cache_spec.max_blocks_per_slot),
                kv_cache.DEAD_BLOCK, np.int32)
        else:
            self.cache_spec = kv_cache.KVCacheSpec(
                num_layers=model_cfg.num_layers, num_slots=self.max_slots,
                num_heads=model_cfg.num_heads, max_len=self.max_len,
                head_dim=model_cfg.head_dim, dtype=kv_dtype)
            self.cache = kv_cache.init_cache(self.cache_spec, self.mesh)
            self._cache_sh = kv_cache.cache_shardings(self.mesh)
            self.allocator = None
            self.block_tables = None
        self.drafter = NGramDrafter(self.spec_k, self.icfg.spec_ngram) \
            if self.spec_k > 0 else None
        self._spec_proposed = 0
        self._spec_accepted = 0

        # --- host-authoritative per-slot counters ---
        self.lengths = np.zeros(self.max_slots, np.int32)
        self.active = np.zeros(self.max_slots, bool)
        self.last_tokens = np.zeros(self.max_slots, np.int32)
        self._held = set()               # acquired, not yet activated
        self._last_admit: Dict[int, Dict[str, Any]] = {}
        # Why the most recent select_slot returned None ("no_slot" =
        # every slot busy; "reservation" = slots free but the block-pool
        # gate refused the HBM booking). Host state for the scheduler's
        # rejection accounting.
        self.last_admit_block: Optional[str] = None

        # --- telemetry on the shared spine ---
        self.iterations = 0
        self._rng_calls = 0
        self.serving = ServingAggregator(self.max_slots,
                                         label=self.replica or None)
        self._attach_slo_overlays()
        tel_meta = dict(mode="serving", model=model_cfg.name,
                        dp=self.dp, mp=self.mp, sp=self.sp,
                        max_slots=self.max_slots, max_seq_len=self.max_len,
                        prefill_chunk=self.prefill_chunk,
                        block_size=self.block_size,
                        num_blocks=self.num_blocks if self.paged else 0,
                        spec_k=self.spec_k,
                        replica=self.replica,
                        quantize=self.quantize,
                        precision=jnp.dtype(model_cfg.dtype).name,
                        param_bytes=self.param_bytes,
                        kv_cache_bytes=self.cache_spec.nbytes())
        if self.paged:
            # Analytic attend pricing (both ways, per generated token at
            # the bounds): the kernel term scales with live context
            # (ceil(ctx/bs)*bs — quoted at ctx = max_seq_len), the
            # one-hot term with pool CAPACITY. Projections, not device
            # measurements — the structural ratio SERVE_BENCH reports.
            self.serving.attend_mode = ("kernel" if self.paged_kernel
                                        else "onehot")
            sp_ = self.cache_spec
            kvi = jnp.dtype(sp_.dtype).itemsize
            tel_meta["paged_kernel"] = self.paged_kernel
            tel_meta["attend_flops_per_token"] = {
                "live_ctx_max": paged_attn_ops.attend_flops_per_token(
                    sp_.num_heads, sp_.head_dim, sp_.block_size,
                    context=sp_.max_len, num_layers=sp_.num_layers),
                "pool_capacity": paged_attn_ops.attend_flops_per_token(
                    sp_.num_heads, sp_.head_dim, sp_.block_size,
                    pool_blocks=sp_.blocks_per_group,
                    num_layers=sp_.num_layers),
                "projection": "analytic"}
            tel_meta["attend_hbm_bytes_per_token"] = {
                "live_ctx_max": paged_attn_ops.attend_hbm_bytes_per_token(
                    sp_.num_heads, sp_.head_dim, sp_.block_size,
                    context=sp_.max_len, kv_itemsize=kvi,
                    num_layers=sp_.num_layers),
                "pool_capacity": paged_attn_ops.attend_hbm_bytes_per_token(
                    sp_.num_heads, sp_.head_dim, sp_.block_size,
                    pool_blocks=sp_.blocks_per_group, kv_itemsize=kvi,
                    num_layers=sp_.num_layers),
                "projection": "analytic"}
        self.telemetry = Telemetry(
            self.tcfg, default_report_steps=50, meta=tel_meta)
        _ref = weakref.ref(self)
        self.telemetry.step_provider = lambda: (
            _ref().iterations if _ref() is not None else -1)
        self.telemetry.set_analytic_footprint(analytic_state_bytes(
            {"params": self._params, "cache": self.cache}))

        # --- the compiled paths (sentinel-instrumented): decode +
        # prefill always; paged engines add the copy-on-write block copy
        # and, with spec_k > 0, the speculative verify step. Each has
        # ONE abstract signature for the engine's lifetime ---
        self._decode_fn = self.telemetry.instrument_step_fn(
            "decode_step", self._build_decode_step())
        self._prefill_fn = self.telemetry.instrument_step_fn(
            "prefill_step", self._build_prefill_step())
        if self.paged:
            self._copy_fn = self.telemetry.instrument_step_fn(
                "copy_block", self._build_copy_block())
        if self.paged and self.spec_k > 0:
            self._verify_fn = self.telemetry.instrument_step_fn(
                "verify_step", self._build_verify_step())

        layout = (f"paged bs={self.block_size} x{self.num_blocks} blocks"
                  if self.paged else "slot-major")
        log_dist(
            f"InferenceEngine initialized: {model_cfg.name}, "
            f"slots={self.max_slots} (dp={self.dp}), "
            f"cache={layout} {self.max_len}x{model_cfg.num_heads}h "
            f"({self.cache_spec.nbytes() / 2 ** 20:.1f} MiB K+V), "
            f"prefill={'full' if self.prefill_chunk == 0 else f'chunk {self.prefill_chunk}'}, "
            f"spec_k={self.spec_k}, quantize={self.quantize}"
            + (f", replica={self.replica}" if self.replica else ""),
            ranks=[0])

    # ------------------------------------------------------------------ #
    # Compiled-path builders
    # ------------------------------------------------------------------ #
    def _runtime_params(self, params):
        """Dequantize inside the compiled program (int8 at rest,
        compute-dtype transients); identity for none/bf16."""
        if self.quantize == "int8":
            return dequantize(params, self.model_cfg.dtype)
        return params

    def _build_decode_step(self) -> Callable:
        cfg = self.model_cfg
        dp = self.dp

        def decode_step(params, kc, vc, tokens, lengths, bt, key,
                        temperature):
            p = self._runtime_params(params)
            if self.paged:
                logits, kc, vc = decode_mod.gpt2_decode_paged(
                    p, kc, vc, tokens, lengths, bt, cfg, dp,
                    paged_kernel=self.paged_kernel, mesh=self.mesh)
            else:
                logits, kc, vc = decode_mod.gpt2_decode(p, kc, vc,
                                                        tokens, lengths,
                                                        cfg)
            sampled = decode_mod.sample_tokens(logits, key, temperature)
            return kc, vc, sampled, logits

        sh = self._cache_sh
        return jax.jit(decode_step, donate_argnums=(1, 2),
                       out_shardings=(sh["k"], sh["v"], None, None))

    def _build_prefill_step(self) -> Callable:
        cfg = self.model_cfg
        dp = self.dp
        attention_fn = None
        if self.prefill_chunk == 0 and self.sp > 1:
            from ..ops.ring_attention import ring_attention_fn
            attention_fn = ring_attention_fn(self.mesh)
        sh = self._cache_sh

        if self.paged and self.prefill_chunk > 0:
            # Group-batched chunked prefill: one chunk of one slot per
            # dp group (single admissions leave the other groups' rows
            # DEAD — uniform program, writes land nowhere).
            def prefill_step(params, kc, vc, tokens, bt_rows, start,
                             last_idx, active, key, temperature):
                p = self._runtime_params(params)
                logits, kc, vc = decode_mod.gpt2_prefill_chunk_paged(
                    p, kc, vc, tokens, bt_rows, start, last_idx,
                    active, cfg, paged_kernel=self.paged_kernel,
                    mesh=self.mesh)
                sampled = decode_mod.sample_tokens(logits, key,
                                                   temperature)
                return kc, vc, sampled, logits
        elif self.paged:
            def prefill_step(params, kc, vc, tokens, bt_rows, last_idx,
                             key, temperature):
                p = self._runtime_params(params)
                logits, kc, vc = decode_mod.gpt2_prefill_full_paged(
                    p, kc, vc, tokens, bt_rows, last_idx, cfg,
                    attention_fn=attention_fn)
                sampled = decode_mod.sample_tokens(logits, key,
                                                   temperature)
                return kc, vc, sampled, logits
        else:
            def prefill_step(params, kc, vc, tokens, slot, start,
                             last_idx, key, temperature):
                p = self._runtime_params(params)
                if self.prefill_chunk == 0:
                    logits, kc, vc = decode_mod.gpt2_prefill_full(
                        p, kc, vc, tokens, slot, last_idx, cfg,
                        attention_fn=attention_fn)
                else:
                    logits, kc, vc = decode_mod.gpt2_prefill_chunk(
                        p, kc, vc, tokens, slot, start, last_idx, cfg)
                sampled = decode_mod.sample_tokens(logits, key,
                                                   temperature)
                return kc, vc, sampled, logits

        return jax.jit(prefill_step, donate_argnums=(1, 2),
                       out_shardings=(sh["k"], sh["v"], None, None))

    def _build_verify_step(self) -> Callable:
        """Speculative draft-then-verify: one batched K=spec_k+1 step,
        in-graph acceptance (decode.spec_accept), ONE [S, K+2] int32
        readback — the same single host fetch per iteration plain
        decode pays."""
        cfg = self.model_cfg
        dp = self.dp

        def verify_step(params, kc, vc, tokens, lengths, bt, key,
                        temperature):
            p = self._runtime_params(params)
            logits, kc, vc = decode_mod.gpt2_verify_paged(
                p, kc, vc, tokens, lengths, bt, cfg, dp,
                paged_kernel=self.paged_kernel, mesh=self.mesh)
            out = decode_mod.spec_accept(logits, tokens, key, temperature)
            return kc, vc, out, logits

        sh = self._cache_sh
        return jax.jit(verify_step, donate_argnums=(1, 2),
                       out_shardings=(sh["k"], sh["v"], None, None))

    def _build_copy_block(self) -> Callable:
        """The device half of copy-on-write: duplicate one block's K/V
        rows (all layers) into a private block of the same group."""
        def copy_block(kc, vc, src_onehot, dst_onehot):
            return (kv_cache.paged_copy_block(kc, src_onehot, dst_onehot),
                    kv_cache.paged_copy_block(vc, src_onehot, dst_onehot))

        sh = self._cache_sh
        return jax.jit(copy_block, donate_argnums=(0, 1),
                       out_shardings=(sh["k"], sh["v"]))

    def _next_key(self) -> jax.Array:
        self._rng_calls += 1
        return jax.random.fold_in(self._base_rng, self._rng_calls)

    # ------------------------------------------------------------------ #
    # Slot lifecycle (host counters + block accounting — no device work)
    # ------------------------------------------------------------------ #
    def activate_slot(self, slot: int, context_len: int,
                      last_token: int) -> None:
        """Mark a freshly prefilled slot live: the cache holds positions
        0..context_len-1 and ``last_token`` decodes at position
        context_len next step."""
        self.lengths[slot] = int(context_len)
        self.active[slot] = True
        self.last_tokens[slot] = int(last_token)
        self._held.discard(slot)
        if self.drafter is not None:
            self.drafter.observe(slot, [int(last_token)])

    def release_slot(self, slot: int) -> None:
        """Evict: counters clear and (paged) every block reference
        drops — private blocks return to the free list, prefix blocks
        whose refcount hits zero are LRU-retained for future hits. The
        stale rows are dead by masking either way."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_tokens[slot] = 0
        self._held.discard(slot)
        if self.paged:
            row = self.block_tables[slot]
            self.allocator.release(
                slot, [int(b) for b in row if b != kv_cache.DEAD_BLOCK])
            row[:] = kv_cache.DEAD_BLOCK
        if self.drafter is not None:
            self.drafter.reset(slot)

    def context_len(self, slot: int) -> int:
        return int(self.lengths[slot])

    @property
    def active_slots(self) -> int:
        return int(self.active.sum())

    @property
    def spec_enabled(self) -> bool:
        return self.paged and self.spec_k > 0

    def _ensure_blocks(self, slot: int, upto_pos: int) -> None:
        """Lazily allocate table entries so ``slot`` can write token
        positions up to ``upto_pos`` — the per-iteration HBM growth the
        hbm_bytes_per_token metric tracks."""
        J = self.cache_spec.max_blocks_per_slot
        need_j = min(upto_pos // self.block_size, J - 1)
        row = self.block_tables[slot]
        j = int((row != kv_cache.DEAD_BLOCK).sum())
        while j <= need_j:
            row[j] = self.allocator.alloc_block(slot)
            j += 1

    # ------------------------------------------------------------------ #
    # Admission (the scheduler's gate): slot occupancy AND HBM blocks
    # ------------------------------------------------------------------ #
    def group_of(self, slot: int) -> int:
        """The dp group (pool shard) a slot's blocks live in."""
        return slot // self.cache_spec.slots_per_group if self.paged \
            else 0

    def select_slot(self, prompt: Sequence[int],
                    max_new_tokens: int = 0,
                    exclude_groups: Optional[set] = None
                    ) -> Optional[int]:
        """Pick and HOLD a free slot for this prompt, or None when the
        engine cannot admit it now.

        Paged engines extend the gate from slot occupancy to HBM
        accounting: a group must cover the request's worst-case block
        need (``BlockAllocator.can_admit``), and among admissible
        groups the one already holding the longest cached prefix of
        this prompt wins (prefix affinity — the request lands where its
        blocks live), ties broken toward the most available HBM. The
        hold is released by ``activate_slot`` or ``release_slot``.
        ``exclude_groups`` lets the scheduler gather a one-slot-per-
        group admission batch for ``prefill_many``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.last_admit_block = None
        free = [s for s in range(self.max_slots)
                if not self.active[s] and s not in self._held]
        if not free:
            self.last_admit_block = "no_slot"
            return None
        if not self.paged:
            self._held.add(free[0])
            return free[0]
        share = self.prefill_chunk > 0
        Sg = self.cache_spec.slots_per_group
        first_free: Dict[int, int] = {}
        for s in free:
            g = s // Sg
            if exclude_groups and g in exclude_groups:
                continue
            first_free.setdefault(g, s)
        best = None
        best_key = None
        for g, s in first_free.items():
            if not self.allocator.can_admit(g, prompt,
                                            int(max_new_tokens),
                                            self.spec_k, share=share):
                continue
            matched = len(self.allocator.match_prefix(g, prompt)[0]) \
                if share else 0
            key = (matched, self.allocator.available(g))
            if best_key is None or key > best_key:
                best, best_key = s, key
        if best is not None:
            self._held.add(best)
        else:
            self.last_admit_block = "reservation"
        return best

    def last_admit_info(self, slot: int) -> Dict[str, Any]:
        """Prefix-cache/CoW detail of the most recent admission into
        ``slot`` (for the request trace); empty for slot-major paths."""
        return self._last_admit.get(slot, {})

    def note_admission_reject(self, rid: Any, reason: str, attempt: int,
                              queue_depth: int = 0) -> None:
        """Count one admission rejection; the FIRST rejection of each
        request also writes a structured telemetry event (the retry loop
        used to be invisible in the stream)."""
        self.serving.note_reject()
        if attempt == 1 and self.telemetry.enabled:
            payload = {"rid": rid, "reason": reason,
                       "queue_depth": int(queue_depth)}
            if self.replica:
                payload["replica"] = self.replica
            self.telemetry.event("admission_rejected", payload)

    def prefix_match_tokens(self, prompt: Sequence[int]) -> int:
        """Longest cached prompt prefix (tokens) resident anywhere in
        this engine's block pool — the router's affinity signal. Host
        hash walk only; zero device work."""
        if not self.paged or self.prefill_chunk == 0:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        best = 0
        for g in range(self.dp):
            best = max(best,
                       len(self.allocator.match_prefix(g, prompt)[0]))
        return best * self.block_size

    # ------------------------------------------------------------------ #
    # The two serving operations
    # ------------------------------------------------------------------ #
    def prefill(self, prompt: Sequence[int], slot: int,
                temperature: float = 0.0, return_logits: bool = False,
                max_new_tokens: Optional[int] = None
                ) -> Tuple[int, Optional[np.ndarray]]:
        """Prefill one prompt into ``slot`` and sample its first output
        token. Returns (token, final-position logits [V] when asked —
        parity tests only; the serving loop needs just the token, and a
        per-admission [V] fetch would be a wasted host transfer). The
        caller activates the slot (scheduler owns admission ordering).

        Paged engines first admit the prompt through the block
        allocator: cached full-block prefixes are shared by refcount
        (only the tail re-prefills — the TTFT win), an exactly-matched
        chain forks its final block copy-on-write before the first
        write, and ``max_new_tokens`` (the scheduler passes the
        request's) books the worst-case HBM reservation so mid-flight
        appends can never strand the slot. Direct calls without it
        reserve nothing and draw from the free pool lazily."""
        t0 = time.perf_counter()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ValueError("empty prompt")
        if plen >= self.max_len:
            raise ValueError(
                f"prompt length {plen} leaves no room to generate in a "
                f"{self.max_len}-token slot")
        kc, vc = self.cache["k"], self.cache["v"]
        temp = np.float32(temperature)
        if not self.paged:
            if self.prefill_chunk == 0:
                padded = np.zeros(self.max_len, np.int32)
                padded[:plen] = prompt
                kc, vc, tok, logits = self._prefill_fn(
                    self._params, kc, vc, padded, np.int32(slot),
                    np.int32(0), np.int32(plen - 1), self._next_key(),
                    temp)
            else:
                chunk = self.prefill_chunk
                n_chunks = -(-plen // chunk)
                padded = np.zeros(n_chunks * chunk, np.int32)
                padded[:plen] = prompt
                tok = logits = None
                for ci in range(n_chunks):
                    start = ci * chunk
                    last = ci == n_chunks - 1
                    last_idx = (plen - 1 - start) if last else 0
                    kc, vc, tok, logits = self._prefill_fn(
                        self._params, kc, vc, padded[start:start + chunk],
                        np.int32(slot), np.int32(start),
                        np.int32(last_idx), self._next_key(), temp)
        elif self.prefill_chunk == 0:
            G = self.dp
            J = self.cache_spec.max_blocks_per_slot
            group = slot // self.cache_spec.slots_per_group
            plan = self.allocator.admit_prompt(
                slot, group, prompt, int(max_new_tokens or 0),
                self.spec_k, share=False)
            row = np.full(J, kv_cache.DEAD_BLOCK, np.int32)
            row[:len(plan.table)] = plan.table
            self.block_tables[slot] = row
            padded = np.zeros(self.max_len, np.int32)
            padded[:plen] = prompt
            bt_rows = np.full((G, J), kv_cache.DEAD_BLOCK, np.int32)
            bt_rows[group] = row
            kc, vc, tok, logits = self._prefill_fn(
                self._params, kc, vc, padded, bt_rows,
                np.int32(plen - 1), self._next_key(), temp)
            if self.drafter is not None:
                self.drafter.begin(slot, prompt)
            self.serving.note_admit(plen, 0)
        else:
            self.cache["k"], self.cache["v"] = kc, vc
            tok, logits = self.prefill_many(
                [(slot, prompt, int(max_new_tokens or 0))], temperature,
                return_logits=return_logits)[0]
            return tok, logits
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        out_logits = np.asarray(jax.device_get(logits)) \
            if return_logits else None
        tok = int(jax.device_get(tok))
        if self.serving.ledger is not None:
            self.serving.ledger.note("prefill", time.perf_counter() - t0)
        return tok, out_logits

    def prefill_many(self, admissions: Sequence[Tuple[int, Any, int]],
                     temperature: float = 0.0,
                     return_logits: bool = False
                     ) -> "list[Tuple[int, Optional[np.ndarray]]]":
        """Batched admission: prefill up to ONE slot per dp group in a
        single pass of group-batched chunk programs.

        ``admissions``: [(slot, prompt, max_new_tokens)] with every slot
        in a DISTINCT group — the scheduler gathers them that way. A
        lone admission leaves the other groups computing masked garbage
        (the uniform program); a full batch does real work in every
        group, which is what keeps saturation-time TTFT flat as dp
        grows: G admissions cost one admission's wall. Copy-on-write
        forks across the batch merge into ONE block-copy call (distinct
        groups can't collide). Returns [(first token, logits|None)] in
        admission order."""
        if not (self.paged and self.prefill_chunk > 0):
            raise RuntimeError("prefill_many needs the paged cache and "
                               "chunked prefill")
        t_pf0 = time.perf_counter()
        G = self.dp
        J = self.cache_spec.max_blocks_per_slot
        Sg = self.cache_spec.slots_per_group
        chunk = self.prefill_chunk
        temp = np.float32(temperature)
        kc, vc = self.cache["k"], self.cache["v"]
        plans = []
        seen_groups = set()
        cow_src = np.zeros((G, self.cache_spec.blocks_per_group),
                           np.float32)
        cow_dst = np.zeros((G, self.cache_spec.blocks_per_group), bool)
        any_cow = False
        for slot, prompt, max_new in admissions:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            plen = int(prompt.shape[0])
            if plen < 1:
                raise ValueError("empty prompt")
            if plen >= self.max_len:
                raise ValueError(
                    f"prompt length {plen} leaves no room to generate "
                    f"in a {self.max_len}-token slot")
            group = slot // Sg
            if group in seen_groups:
                raise ValueError(
                    f"prefill_many: two admissions in group {group} — "
                    "batch at most one slot per dp group")
            seen_groups.add(group)
            plan = self.allocator.admit_prompt(
                slot, group, prompt, int(max_new), self.spec_k)
            row = np.full(J, kv_cache.DEAD_BLOCK, np.int32)
            row[:len(plan.table)] = plan.table
            self.block_tables[slot] = row
            if plan.cow_src is not None:
                cow_src[group, plan.cow_src] = 1.0
                cow_dst[group, plan.cow_dst] = True
                any_cow = True
            plans.append((slot, group, plan, prompt, plen))
        if any_cow:
            kc, vc = self._copy_fn(kc, vc, cow_src, cow_dst)
        # Chunk schedule: admission a runs chunks over its unshared
        # tail; all admissions advance together, groups whose tail is
        # done go inactive (writes land nowhere).
        tails = []
        for slot, group, plan, prompt, plen in plans:
            tlen = plen - plan.matched
            n_chunks = -(-tlen // chunk)
            padded = np.zeros(n_chunks * chunk, np.int32)
            padded[:tlen] = prompt[plan.matched:]
            tails.append((padded, n_chunks, tlen))
            self._last_admit[slot] = {
                "cached_tokens": int(plan.matched), "chunks": n_chunks,
                "cow_fork": plan.cow_src is not None}
        max_chunks = max(n for _, n, _ in tails)
        held = {}                       # slot -> (ci, group) of its last chunk
        steps = []                      # per-ci (tok_g, logits_g) device arrays
        for ci in range(max_chunks):
            toks = np.zeros((G, chunk), np.int32)
            bt_rows = np.full((G, J), kv_cache.DEAD_BLOCK, np.int32)
            starts = np.zeros(G, np.int32)
            last_idxs = np.zeros(G, np.int32)
            act = np.zeros(G, np.int32)
            for (slot, group, plan, prompt, plen), \
                    (padded, n_chunks, tlen) in zip(plans, tails):
                if ci >= n_chunks:
                    continue
                toks[group] = padded[ci * chunk:(ci + 1) * chunk]
                bt_rows[group] = self.block_tables[slot]
                starts[group] = plan.matched + ci * chunk
                act[group] = 1
                if ci == n_chunks - 1:
                    last_idxs[group] = tlen - 1 - ci * chunk
                    held[slot] = (ci, group)
            kc, vc, tok_g, logits_g = self._prefill_fn(
                self._params, kc, vc, toks, bt_rows, starts, last_idxs,
                act, self._next_key(), temp)
            steps.append((tok_g, logits_g))
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        out = []
        for slot, group, plan, prompt, plen in plans:
            ci, g = held[slot]
            tok = int(jax.device_get(steps[ci][0][g]))
            logits = np.asarray(jax.device_get(steps[ci][1][g])) \
                if return_logits else None
            if self.drafter is not None:
                self.drafter.begin(slot, prompt)
            self.serving.note_admit(plen, plan.matched)
            out.append((tok, logits))
        if self.serving.ledger is not None:
            self.serving.ledger.note("prefill",
                                     time.perf_counter() - t_pf0)
        return out

    def _cache_accounting(self) -> Tuple[int, int]:
        """(cache bytes held, context tokens cached) this iteration —
        the hbm_bytes_per_token sample. Slot-major reserves the full
        cache whatever the contexts hold; paged holds only live
        blocks."""
        tokens = int(self.lengths[self.active].sum())
        if self.paged:
            return self.allocator.bytes_in_use(), tokens
        return self.cache_spec.nbytes(), tokens

    def _attend_work(self, k_rows: int) -> Tuple[int, int, int, int]:
        """Analytic attend work of the iteration just run, priced BOTH
        ways: (flops_kernel, flops_onehot, bytes_kernel, bytes_onehot).
        Kernel terms sum each live slot's ceil(ctx/bs)*bs keys (the K
        query rows share the block loads, so HBM bytes don't multiply
        by k_rows); one-hot terms are structural: every slot stream
        scores the whole pool and each dp group streams its full pool
        per layer, occupancy notwithstanding. Projections — host
        arithmetic, no device work."""
        sp_ = self.cache_spec
        kvi = int(jnp.dtype(sp_.dtype).itemsize)
        args = (sp_.num_heads, sp_.head_dim, sp_.block_size)
        ctxs = [max(1, int(c)) for c in self.lengths[self.active]]
        fk = sum(paged_attn_ops.attend_flops_per_token(
            *args, context=c, num_layers=sp_.num_layers)
            for c in ctxs) * k_rows
        bk = sum(paged_attn_ops.attend_hbm_bytes_per_token(
            *args, context=c, kv_itemsize=kvi,
            num_layers=sp_.num_layers) for c in ctxs)
        fo = paged_attn_ops.attend_flops_per_token(
            *args, pool_blocks=sp_.blocks_per_group,
            num_layers=sp_.num_layers) * k_rows * self.max_slots
        bo = paged_attn_ops.attend_hbm_bytes_per_token(
            *args, pool_blocks=sp_.blocks_per_group, kv_itemsize=kvi,
            num_layers=sp_.num_layers) * sp_.num_groups
        return fk, fo, bk, bo

    def decode_once(self, temperature: float = 0.0,
                    return_logits: bool = False
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """One decode iteration for every slot (inactive slots compute
        too — a uniform program is what keeps the signature fixed; their
        counters just don't advance). Returns the sampled token per slot
        (and the [S, V] logits when asked — tests only; the extra fetch
        is not part of the serving loop)."""
        t0 = time.perf_counter()
        self.telemetry.profiler_tick(self.iterations)
        n_active = self.active_slots
        if self.paged:
            for s in np.flatnonzero(self.active):
                self._ensure_blocks(int(s), int(self.lengths[s]))
            bt = self.block_tables
        else:
            bt = np.int32(0)            # unused by the slot-major path
        kc, vc, sampled, logits = self._decode_fn(
            self._params, self.cache["k"], self.cache["v"],
            self.last_tokens, self.lengths, bt, self._next_key(),
            np.float32(temperature))
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        # THE serving sync: the host needs the tokens (EOS detection +
        # next step's inputs). One batched [S] fetch per iteration.
        sampled = np.asarray(jax.device_get(sampled))
        adv = self.active
        self.lengths[adv] += 1
        self.last_tokens[adv] = sampled[adv]
        if self.drafter is not None:
            for s in np.flatnonzero(adv):
                self.drafter.observe(int(s), [int(sampled[s])])
        wall = time.perf_counter() - t0
        self.iterations += 1
        cache_bytes, ctx_tokens = self._cache_accounting()
        self.serving.note_iteration(n_active, wall,
                                    cache_bytes=cache_bytes,
                                    context_tokens=ctx_tokens)
        if self.serving.ledger is not None:
            self.serving.ledger.note("decode_useful", wall)
        if self.paged and n_active:
            self.serving.note_attend(*self._attend_work(1), n_active)
        tl = self.telemetry
        if tl.enabled:
            tl.record_step(self.iterations, {},
                           wall_ms=wall * 1e3,
                           active_slots=n_active,
                           occupancy=round(n_active / self.max_slots, 4),
                           tokens=n_active)
            tl.maybe_drain(self.iterations, extra_fn=self._report_extra)
        out_logits = np.asarray(jax.device_get(logits)) \
            if return_logits else None
        return sampled, out_logits

    def spec_decode_once(self, temperature: float = 0.0
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """One speculative draft-then-verify iteration for every slot.

        The n-gram drafter proposes ``spec_k`` tokens per live slot
        (host-side, free), ONE batched verify step scores
        [last, d_1..d_k] through the paged cache, and the in-graph
        acceptance rule emits the longest agreeing prefix plus the
        correction/bonus token — 1..k+1 tokens per slot per iteration,
        greedy-bit-identical to plain decode. Still exactly one host
        fetch. Returns (emitted [S, k+1] int32, n_new [S] — how many
        leading emitted tokens are real per slot; 0 for inactive)."""
        if not self.spec_enabled:
            raise RuntimeError("spec_decode_once needs inference.spec_k "
                               "> 0 and the paged cache")
        if float(temperature) > 0.0:
            raise ValueError(
                "spec_decode_once is greedy-only (the acceptance rule "
                "has no rejection-sampling correction); use "
                "decode_once for temperature > 0 — the scheduler falls "
                "back automatically")
        t0 = time.perf_counter()
        self.telemetry.profiler_tick(self.iterations)
        k = self.spec_k
        n_active = self.active_slots
        toks = np.zeros((self.max_slots, k + 1), np.int32)
        toks[:, 0] = self.last_tokens
        live = np.flatnonzero(self.active)
        for s in live:
            s = int(s)
            toks[s, 1:] = self.drafter.propose(s)
            self._ensure_blocks(
                s, min(int(self.lengths[s]) + k, self.max_len - 1))
        kc, vc, out, logits = self._verify_fn(
            self._params, self.cache["k"], self.cache["v"], toks,
            self.lengths, self.block_tables, self._next_key(),
            np.float32(temperature))
        self.cache["k"], self.cache["v"] = kc, vc
        self.telemetry.raise_pending()
        out = np.asarray(jax.device_get(out))        # [S, k+2]
        n_new = out[:, 0].copy()
        emitted = out[:, 1:]
        n_new[~self.active] = 0
        accepted = 0
        for s in live:
            s = int(s)
            n = max(0, min(int(n_new[s]),
                           self.max_len - int(self.lengths[s])))
            n_new[s] = n
            if n == 0:
                continue
            self.lengths[s] += n
            self.last_tokens[s] = int(emitted[s, n - 1])
            self.drafter.observe(s, emitted[s, :n])
            accepted += n - 1
        emitted_total = int(n_new.sum())
        self._spec_proposed += k * len(live)
        self._spec_accepted += accepted
        wall = time.perf_counter() - t0
        self.iterations += 1
        cache_bytes, ctx_tokens = self._cache_accounting()
        self.serving.note_iteration(n_active, wall,
                                    cache_bytes=cache_bytes,
                                    context_tokens=ctx_tokens,
                                    emitted_tokens=emitted_total)
        if self.serving.ledger is not None:
            # Split the verify wall by row share: of the (k+1) verify
            # rows per live slot, the emitted tokens (accepted drafts +
            # the correction/bonus) are useful work; the rejected drafts
            # are wall the draft caused and the target threw away.
            rows = (k + 1) * len(live)
            wasted = wall * (k * len(live) - accepted) / rows \
                if rows else 0.0
            self.serving.ledger.note("spec_wasted", wasted)
            self.serving.ledger.note("decode_useful", wall - wasted)
        if n_active and emitted_total:
            self.serving.note_attend(*self._attend_work(k + 1),
                                     emitted_total)
        self.serving.note_spec(k * len(live), accepted)
        tl = self.telemetry
        if tl.enabled:
            tl.record_step(self.iterations, {},
                           wall_ms=wall * 1e3,
                           active_slots=n_active,
                           occupancy=round(n_active / self.max_slots, 4),
                           tokens=emitted_total,
                           spec_accepted=accepted)
            tl.maybe_drain(self.iterations, extra_fn=self._report_extra)
        return emitted, n_new

    def _attach_slo_overlays(self) -> None:
        """Attach the serving goodput ledger (always — host arithmetic)
        and, when ``inference.slo`` sets a target, the SLO tracker."""
        self.serving.ledger = ServingGoodputLedger(
            label=self.replica or None)
        scfg = self.icfg.slo
        if scfg.enabled:
            self.serving.slo = SLOTracker(
                ttft_ms=scfg.ttft_ms, tpot_ms=scfg.tpot_ms,
                availability=scfg.availability, window_s=scfg.window_s)

    def reset_serving_stats(self) -> None:
        """Fresh aggregator window (benches call this after a warmup
        pass so compile time never pollutes the measured TTFT/TPOT
        stream — both sides of a comparison warm the same way)."""
        self.serving = ServingAggregator(self.max_slots,
                                         label=self.replica or None)
        if self.paged:
            self.serving.attend_mode = ("kernel" if self.paged_kernel
                                        else "onehot")
        self._attach_slo_overlays()
        self._spec_proposed = 0
        self._spec_accepted = 0

    def _report_extra(self) -> Dict[str, Any]:
        return {"serving": self.serving.snapshot()}

    def profile_window(self, steps: int,
                       start_step: Optional[int] = None) -> Optional[str]:
        """Arm a ``jax.profiler`` capture over ``steps`` decode
        iterations (default: starting at the next iteration). The trace
        is ingested and reconciled at the next telemetry drain
        (``telemetry.profile`` block); with telemetry off this is a
        no-op returning None. Zero device syncs are added when no window
        is armed — the PR-4 fence contract."""
        return self.telemetry.arm_profile_window(
            int(steps), start_step=self.iterations + 1
            if start_step is None else int(start_step))

    def complete_request(self, rid: Any, ttft_s: float,
                         tpot_s: Optional[float], prompt_tokens: int,
                         new_tokens: int,
                         queue_wait_s: Optional[float] = None,
                         service_ttft_s: Optional[float] = None,
                         admission_attempts: Optional[int] = None) -> None:
        """Per-request goodput accounting at completion (host clocks
        only): feeds the aggregator and SLO tracker and writes a
        ``request_complete`` telemetry event. ``queue_wait_s`` /
        ``service_ttft_s`` split the TTFT at the admission instant."""
        self.serving.note_request(ttft_s, tpot_s, new_tokens,
                                  queue_wait_s=queue_wait_s,
                                  service_ttft_s=service_ttft_s,
                                  admission_attempts=admission_attempts)
        if self.serving.slo is not None:
            self.serving.slo.observe(ttft_s, tpot_s)
        if self.telemetry.enabled:
            payload = {"rid": rid, "ttft_ms": round(ttft_s * 1e3, 3),
                       "prompt_tokens": int(prompt_tokens),
                       "new_tokens": int(new_tokens)}
            if tpot_s is not None:
                payload["tpot_ms"] = round(tpot_s * 1e3, 3)
            if queue_wait_s is not None:
                payload["queue_wait_ms"] = round(queue_wait_s * 1e3, 3)
            if service_ttft_s is not None:
                payload["service_ttft_ms"] = round(service_ttft_s * 1e3, 3)
            if admission_attempts:
                payload["admission_attempts"] = int(admission_attempts)
            if self.replica:
                payload["replica"] = self.replica
            self.telemetry.event("request_complete", payload)

    def abort_request(self, rid: Any, reason: str = "abort") -> None:
        """An aborted/evicted request: counts against SLO availability
        and leaves a structured event in the stream."""
        if self.serving.slo is not None:
            self.serving.slo.observe_failure()
        if self.telemetry.enabled:
            payload = {"rid": rid, "reason": reason}
            if self.replica:
                payload["replica"] = self.replica
            self.telemetry.event("request_abort", payload)

    def serve(self, requests, temperature: float = 0.0, **kwargs):
        """Drive a request list/stream through the continuous-batching
        scheduler; see inference/scheduler.py."""
        from .scheduler import ContinuousBatchingScheduler
        sched = ContinuousBatchingScheduler(self, temperature=temperature,
                                            **kwargs)
        return sched.serve(requests)

    # ------------------------------------------------------------------ #
    # Training-checkpoint handoff
    # ------------------------------------------------------------------ #
    @classmethod
    def from_train_checkpoint(cls, load_dir: str, model_cfg: GPT2Config,
                              config: Any = None, tag: Optional[str] = None,
                              mesh: Optional[Mesh] = None,
                              rng: Optional[jax.Array] = None,
                              init_fn: Optional[Callable] = None
                              ) -> "InferenceEngine":
        """Build a serving engine from a training engine's checkpoint
        directory (the ``latest``-pointer + ``mp_rank_00`` layout
        runtime/engine.py saves). ``init_fn(rng, cfg) -> params``
        defaults to ``models.gpt2.gpt2_init`` and is only used for its
        tree STRUCTURE (eval_shape — no real init runs)."""
        if flax_serialization is None:
            raise RuntimeError("flax is required to read checkpoints")
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.isfile(latest):
                raise FileNotFoundError(f"no 'latest' pointer in {load_dir}")
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        model_file = os.path.join(path, "mp_rank_00_model_states.msgpack")
        if not os.path.isfile(model_file):
            raise NotImplementedError(
                f"{model_file} not found — TP-sharded (mp_rank_XX) "
                "checkpoints need assembly, load them through the "
                "training engine and pass raw params instead")
        if init_fn is None:
            from ..models.gpt2 import gpt2_init
            init_fn = gpt2_init
        template = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda r: init_fn(r, model_cfg),
                           jax.random.PRNGKey(0)))
        with open(model_file, "rb") as f:
            blob = flax_serialization.from_bytes({"module": template},
                                                 f.read())
        log_dist(f"serving from training checkpoint {path}", ranks=[0])
        return cls(model_cfg, blob["module"], config=config, mesh=mesh,
                   rng=rng)

    # ------------------------------------------------------------------ #
    # Static lint audit (analysis/) — duck-typed lint_engine contract
    # ------------------------------------------------------------------ #
    def _lint_path_meta(self, name: str) -> Dict[str, Any]:
        """Pass metadata for the serving paths: no gradient sync exists
        here, so collective_placement is inert; materialization scales
        from the PER-DEVICE params+cache footprint (matching the
        post-partitioning shapes in the compiled HLO), with the largest
        per-device leaf exempt as usual.

        ``paged_score_bytes`` declares the one-hot contraction's known
        fp32 score transient ([G, Q, K, nH, B, bs] per layer — it
        scales with pool CAPACITY, so a grown pool under a fixed param
        footprint would otherwise trip the fraction-of-declared
        watermark with no code change). Declaring it keeps the budget
        exact: the audit headroom covers exactly that transient, and
        anything bigger — a real full-pool K/V gather carries the extra
        head_dim factor — still fires. With the Pallas kernel on the
        transient does not exist, no budget is declared, and a clean
        materialization pass IS the proof the kernel path materializes
        nothing pool-sized."""
        state = {"params": self._params, "cache": self.cache}
        per_dev_leaves = []
        for leaf in jax.tree_util.tree_leaves(state):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and hasattr(sharding, "shard_shape"):
                try:
                    shape = sharding.shard_shape(tuple(shape))
                except Exception:
                    pass
            per_dev_leaves.append(
                int(np.prod(shape)) * jnp.dtype(leaf.dtype).itemsize)
        score_bytes = 0
        if self.paged and not self.paged_kernel:
            sp_ = self.cache_spec
            q_streams = {"decode_step": (sp_.slots_per_group, 1),
                         "verify_step": (sp_.slots_per_group,
                                         self.spec_k + 1),
                         "prefill_step": (1, self.prefill_chunk)}
            q_, k_ = q_streams.get(name, (0, 0))
            if q_ and k_:
                nh_loc = max(1, sp_.num_heads // self.mp)
                pool_keys = sp_.blocks_per_group * sp_.block_size
                score_bytes = max(
                    q_ * k_ * nh_loc * pool_keys * 4,       # s_all / wb
                    q_ * sp_.max_blocks_per_slot
                    * sp_.blocks_per_group * 4)             # selector
        return {
            "grad_sync_path": False,
            "grad_sync_mode": "none",
            "gas": 1,
            "scatterable_leaf_bytes": [],
            "declared_state_bytes": int(analytic_state_bytes(state)),
            "param_bytes_full": int(self.param_bytes),
            "largest_leaf_bytes": max(per_dev_leaves, default=0),
            "paged_score_bytes": int(score_bytes),
            "dp": self.dp,
            "zero_stage": 0,
        }

    def lint_audit(self, config=None, waivers=None, passes=None):
        """Compile-time lint over the decode/prefill paths (host-side
        AOT re-lower from the sentinel registry; zero device fences).
        The serving contract: host_sync and materialization clean — no
        full-cache gather, no in-step host transfer."""
        from ..analysis.auditor import lint_engine
        return lint_engine(self, config=config, waivers=waivers,
                           passes=passes)

    def close(self) -> None:
        self.telemetry.close()


__all__ = ["InferenceEngine"]
