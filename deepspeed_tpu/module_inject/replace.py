"""HF Flax <-> deepspeed_tpu stacked-block weight mapping.

Reference mapping being reproduced (module_inject/inject.py:27-41): the
separate q/k/v projection weights concatenate into one fused qkv tensor;
attention-output/LayerNorm/FFN tensors map 1:1. Both directions are exact
(copy, no recompute), so inject -> restore is the identity.

Layout notes:
- HF Flax BERT uses flax Dense kernels of shape [in, out] — same as ours.
- HF Flax GPT-2 uses Conv1D kernels stored TRANSPOSED ([out, in]); qkv is
  already fused in ``c_attn`` with q,k,v order matching our split.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig


def _stack(layers, path):
    out = [l for l in layers]
    for key in path:
        out = [l[key] for l in out]
    return jnp.stack([jnp.asarray(x) for x in out])


# --------------------------------------------------------------------- #
# BERT (post-LN encoder)
# --------------------------------------------------------------------- #
def bert_config_from_hf(hf_config) -> TransformerConfig:
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"module injection supports GELU activations only, got "
            f"hidden_act='{act}' (the fused blocks compute GELU; injecting "
            "would silently change the model)")
    return TransformerConfig(
        hidden_size=hf_config.hidden_size,
        num_heads=hf_config.num_attention_heads,
        num_layers=hf_config.num_hidden_layers,
        intermediate_size=hf_config.intermediate_size,
        max_seq_length=hf_config.max_position_embeddings,
        vocab_size=hf_config.vocab_size,
        pre_layer_norm=False,              # original BERT is post-LN
        hidden_dropout=hf_config.hidden_dropout_prob,
        attn_dropout=hf_config.attention_probs_dropout_prob,
        layer_norm_eps=hf_config.layer_norm_eps,
        causal=False,
        gelu_exact=act == "gelu",          # HF "gelu" is the erf form
    )


def extract_bert_encoder(hf_params: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
    """FlaxBertModel params -> stacked block params (qkv concat)."""
    layer_dict = hf_params["encoder"]["layer"]
    layers = [layer_dict[str(i)] for i in range(len(layer_dict))]

    def cat_qkv(which):
        parts = []
        for l in layers:
            s = l["attention"]["self"]
            parts.append(jnp.concatenate(
                [jnp.asarray(s[n][which]) for n in ("query", "key", "value")],
                axis=-1))
        return jnp.stack(parts)

    return {
        "ln1_scale": _stack(layers, ("attention", "output", "LayerNorm",
                                     "scale")),
        "ln1_bias": _stack(layers, ("attention", "output", "LayerNorm",
                                    "bias")),
        "qkv_kernel": cat_qkv("kernel"),
        "qkv_bias": cat_qkv("bias"),
        "proj_kernel": _stack(layers, ("attention", "output", "dense",
                                       "kernel")),
        "proj_bias": _stack(layers, ("attention", "output", "dense", "bias")),
        "ln2_scale": _stack(layers, ("output", "LayerNorm", "scale")),
        "ln2_bias": _stack(layers, ("output", "LayerNorm", "bias")),
        "fc_kernel": _stack(layers, ("intermediate", "dense", "kernel")),
        "fc_bias": _stack(layers, ("intermediate", "dense", "bias")),
        "fc_out_kernel": _stack(layers, ("output", "dense", "kernel")),
        "fc_out_bias": _stack(layers, ("output", "dense", "bias")),
    }


def restore_bert_encoder(stacked: Dict[str, jnp.ndarray],
                         hf_params: Dict[str, Any]) -> Dict[str, Any]:
    """Stacked block params -> a NEW HF param tree (inject.py's reverse
    copy). ``hf_params`` supplies the non-encoder subtrees unchanged."""
    out = _clone(hf_params)
    L = stacked["ln1_scale"].shape[0]
    H = stacked["ln1_scale"].shape[1]
    for i in range(L):
        l = out["encoder"]["layer"][str(i)]
        qkv_k = np.asarray(stacked["qkv_kernel"][i])
        qkv_b = np.asarray(stacked["qkv_bias"][i])
        s = l["attention"]["self"]
        for j, n in enumerate(("query", "key", "value")):
            s[n]["kernel"] = qkv_k[:, j * H:(j + 1) * H]
            s[n]["bias"] = qkv_b[j * H:(j + 1) * H]
        l["attention"]["output"]["dense"]["kernel"] = \
            np.asarray(stacked["proj_kernel"][i])
        l["attention"]["output"]["dense"]["bias"] = \
            np.asarray(stacked["proj_bias"][i])
        l["attention"]["output"]["LayerNorm"]["scale"] = \
            np.asarray(stacked["ln1_scale"][i])
        l["attention"]["output"]["LayerNorm"]["bias"] = \
            np.asarray(stacked["ln1_bias"][i])
        l["intermediate"]["dense"]["kernel"] = \
            np.asarray(stacked["fc_kernel"][i])
        l["intermediate"]["dense"]["bias"] = np.asarray(stacked["fc_bias"][i])
        l["output"]["dense"]["kernel"] = \
            np.asarray(stacked["fc_out_kernel"][i])
        l["output"]["dense"]["bias"] = np.asarray(stacked["fc_out_bias"][i])
        l["output"]["LayerNorm"]["scale"] = np.asarray(stacked["ln2_scale"][i])
        l["output"]["LayerNorm"]["bias"] = np.asarray(stacked["ln2_bias"][i])
    return out


# --------------------------------------------------------------------- #
# GPT-2 (pre-LN decoder; Conv1D = transposed kernels, qkv already fused)
# --------------------------------------------------------------------- #
def gpt2_config_from_hf(hf_config) -> TransformerConfig:
    return TransformerConfig(
        hidden_size=hf_config.n_embd,
        num_heads=hf_config.n_head,
        num_layers=hf_config.n_layer,
        intermediate_size=getattr(hf_config, "n_inner", None) or
        4 * hf_config.n_embd,
        max_seq_length=hf_config.n_positions,
        vocab_size=hf_config.vocab_size,
        pre_layer_norm=True,
        hidden_dropout=hf_config.resid_pdrop,
        attn_dropout=hf_config.attn_pdrop,
        layer_norm_eps=hf_config.layer_norm_epsilon,
        causal=True,
        gelu_exact=False,                  # GPT-2 uses gelu_new (tanh)
    )


def extract_gpt2_blocks(hf_params: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
    h = hf_params["h"]
    layers = [h[str(i)] for i in range(len(h))]

    def stackT(path):
        return jnp.stack([jnp.asarray(_get(l, path)).T for l in layers])

    return {
        "ln1_scale": _stack(layers, ("ln_1", "scale")),
        "ln1_bias": _stack(layers, ("ln_1", "bias")),
        "qkv_kernel": stackT(("attn", "c_attn", "kernel")),
        "qkv_bias": _stack(layers, ("attn", "c_attn", "bias")),
        "proj_kernel": stackT(("attn", "c_proj", "kernel")),
        "proj_bias": _stack(layers, ("attn", "c_proj", "bias")),
        "ln2_scale": _stack(layers, ("ln_2", "scale")),
        "ln2_bias": _stack(layers, ("ln_2", "bias")),
        "fc_kernel": stackT(("mlp", "c_fc", "kernel")),
        "fc_bias": _stack(layers, ("mlp", "c_fc", "bias")),
        "fc_out_kernel": stackT(("mlp", "c_proj", "kernel")),
        "fc_out_bias": _stack(layers, ("mlp", "c_proj", "bias")),
    }


def restore_gpt2_blocks(stacked: Dict[str, jnp.ndarray],
                        hf_params: Dict[str, Any]) -> Dict[str, Any]:
    out = _clone(hf_params)
    L = stacked["ln1_scale"].shape[0]
    for i in range(L):
        l = out["h"][str(i)]
        l["ln_1"]["scale"] = np.asarray(stacked["ln1_scale"][i])
        l["ln_1"]["bias"] = np.asarray(stacked["ln1_bias"][i])
        l["attn"]["c_attn"]["kernel"] = np.asarray(stacked["qkv_kernel"][i]).T
        l["attn"]["c_attn"]["bias"] = np.asarray(stacked["qkv_bias"][i])
        l["attn"]["c_proj"]["kernel"] = np.asarray(stacked["proj_kernel"][i]).T
        l["attn"]["c_proj"]["bias"] = np.asarray(stacked["proj_bias"][i])
        l["ln_2"]["scale"] = np.asarray(stacked["ln2_scale"][i])
        l["ln_2"]["bias"] = np.asarray(stacked["ln2_bias"][i])
        l["mlp"]["c_fc"]["kernel"] = np.asarray(stacked["fc_kernel"][i]).T
        l["mlp"]["c_fc"]["bias"] = np.asarray(stacked["fc_bias"][i])
        l["mlp"]["c_proj"]["kernel"] = np.asarray(stacked["fc_out_kernel"][i]).T
        l["mlp"]["c_proj"]["bias"] = np.asarray(stacked["fc_out_bias"][i])
    return out


# --------------------------------------------------------------------- #
def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _clone(tree):
    if isinstance(tree, dict):
        return {k: _clone(v) for k, v in tree.items()}
    return np.asarray(tree)
