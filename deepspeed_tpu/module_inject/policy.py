"""Generic policy-driven module replacement.

Parity target: reference ``module_inject/replace_module.py:160-192`` —
``replace_module(model, orig_class, replace_fn)`` walks any torch module
tree and swaps instances matched by a policy dict. The repo's round-4
injection was hand-written per architecture (BERT, GPT-2); this module is
the missing REGISTRY mechanism a user can extend without touching repo
code.

TPU-native form: a "module" is a param subtree + an apply fn, so a policy
is four pure functions over config/param pytrees:

  detect(hf_config)          -> does this policy own the architecture?
  config_from_hf(hf_config)  -> TransformerConfig for the fused blocks
  extract(hf_params)         -> stacked [L, ...] block params
  restore(stacked, hf_params)-> a NEW HF param tree (reverse copy)

``replace_module`` is the user entry point: detect (or name) a policy,
return ``(cfg, stacked, restore_fn)``. ``replace_subtrees`` is the
low-level tree walker — the functional analogue of the reference's
recursive ``_replace_module`` — for users who need subtree-level surgery
rather than a whole-architecture swap.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger


@dataclasses.dataclass(frozen=True)
class InjectionPolicy:
    """One architecture's injection recipe (reference HFBertLayerPolicy
    et al., module_inject/replace_policy.py)."""
    name: str
    detect: Callable[[Any], bool]
    config_from_hf: Callable[[Any], Any]
    extract: Callable[[Dict[str, Any]], Dict[str, Any]]
    restore: Callable[[Dict[str, Any], Dict[str, Any]], Dict[str, Any]]


_REGISTRY: "OrderedDict[str, InjectionPolicy]" = OrderedDict()


def register_policy(policy: InjectionPolicy, override: bool = False) -> None:
    """Add an architecture policy. Registration order is detection order
    (first match wins), so register more specific policies first."""
    if policy.name in _REGISTRY and not override:
        raise ValueError(f"injection policy '{policy.name}' already "
                         "registered (pass override=True to replace it)")
    _REGISTRY[policy.name] = policy


def get_policy(name: str) -> InjectionPolicy:
    if name not in _REGISTRY:
        raise KeyError(f"no injection policy '{name}'; registered: "
                       f"{list(_REGISTRY)}")
    return _REGISTRY[name]


def registered_policies() -> List[str]:
    return list(_REGISTRY)


def detect_policy(hf_config) -> InjectionPolicy:
    """First registered policy whose ``detect`` accepts the config."""
    for pol in _REGISTRY.values():
        if pol.detect(hf_config):
            return pol
    raise ValueError(
        f"no registered injection policy matches config "
        f"{type(hf_config).__name__} (model_type="
        f"{getattr(hf_config, 'model_type', None)!r}); registered: "
        f"{list(_REGISTRY)}. Register one with "
        "deepspeed_tpu.module_inject.register_policy")


def replace_module(hf_config, hf_params: Dict[str, Any],
                   policy: Optional[Any] = None
                   ) -> Tuple[Any, Dict[str, Any], Callable]:
    """Swap an HF model's transformer layers for the fused TPU blocks.

    The generic entry the reference exposes as ``replace_module``
    (replace_module.py:160-178): ``policy`` may be a registry name, an
    InjectionPolicy, or None (auto-detect from ``hf_config``). Returns
    ``(cfg, stacked, restore_fn)`` where ``restore_fn(new_stacked)``
    rebuilds the HF param tree (the reverse copy).
    """
    if policy is None:
        pol = detect_policy(hf_config)
    elif isinstance(policy, str):
        pol = get_policy(policy)
    else:
        pol = policy
    logger.info(f"module_inject: applying policy '{pol.name}'")
    cfg = pol.config_from_hf(hf_config)
    stacked = pol.extract(hf_params)

    def restore_fn(new_stacked: Dict[str, Any]) -> Dict[str, Any]:
        return pol.restore(new_stacked, hf_params)

    return cfg, stacked, restore_fn


def replace_subtrees(tree: Dict[str, Any],
                     policies: List[Tuple[Callable, Callable]]
                     ) -> Dict[str, Any]:
    """Recursive subtree replacement over a nested-dict param tree — the
    functional analogue of the reference's ``_replace_module``
    (replace_module.py:175-192, named_children recursion + setattr).

    ``policies``: list of ``(match_fn, replace_fn)``; ``match_fn(path,
    subtree) -> bool`` with ``path`` a '/'-joined key string, and
    ``replace_fn(subtree) -> new_subtree``. First matching policy wins and
    its result is NOT recursed into. Returns a new tree (input unmutated).
    """
    def walk(node, path):
        for match_fn, replace_fn in policies:
            if match_fn(path, node):
                return replace_fn(node)
        if isinstance(node, dict):
            return {k: walk(v, f"{path}/{k}" if path else str(k))
                    for k, v in node.items()}
        return node

    return walk(tree, "")


# --------------------------------------------------------------------- #
# Built-in policies (the round-4 hand-written mappings, now registered
# through the mechanism they predated).
# --------------------------------------------------------------------- #
def _model_type(hf_config) -> str:
    return str(getattr(hf_config, "model_type", "") or "").lower()


def _register_builtins() -> None:
    from .replace import (bert_config_from_hf, extract_bert_encoder,
                          gpt2_config_from_hf, extract_gpt2_blocks,
                          restore_bert_encoder, restore_gpt2_blocks)

    register_policy(InjectionPolicy(
        name="bert",
        detect=lambda c: _model_type(c) == "bert",
        config_from_hf=bert_config_from_hf,
        extract=extract_bert_encoder,
        restore=restore_bert_encoder))

    # RoBERTa's Flax encoder tree is layout-identical to BERT's
    # (encoder/layer/N/attention/...); only the embedding front differs
    # (+2 reserved positions, handled by SparseAttentionUtils.
    # extend_position_embedding). Registered as its own policy so
    # detection, error messages, and future divergence stay per-arch.
    register_policy(InjectionPolicy(
        name="roberta",
        detect=lambda c: _model_type(c) == "roberta",
        config_from_hf=bert_config_from_hf,
        extract=extract_bert_encoder,
        restore=restore_bert_encoder))

    register_policy(InjectionPolicy(
        name="gpt2",
        detect=lambda c: _model_type(c) == "gpt2",
        config_from_hf=gpt2_config_from_hf,
        extract=extract_gpt2_blocks,
        restore=restore_gpt2_blocks))


_register_builtins()
