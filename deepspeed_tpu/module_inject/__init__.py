"""Module injection — swap HF transformer layers for the fast in-repo blocks.

Parity with reference ``module_inject/inject.py:6-83`` (HF BertLayer weights
copied into DeepSpeedTransformerLayer with qkv concatenation at :27-41) and
``replace_module.py:6-192`` (policy-driven swap + bidirectional copy).

TPU-native form: instead of mutating an nn.Module tree, the injector maps a
HuggingFace *Flax* parameter tree into the stacked block-parameter layout of
``models.transformer`` (one [L, ...] tensor per weight, consumed by
``apply_blocks``'s scan and the Pallas flash-attention path), and back. The
"policy" is a pure description of where each weight lives in the HF tree.
"""
from .replace import (bert_config_from_hf, extract_bert_encoder,
                      gpt2_config_from_hf, extract_gpt2_blocks,
                      restore_bert_encoder, restore_gpt2_blocks)
from .policy import (InjectionPolicy, detect_policy, get_policy,
                     register_policy, registered_policies, replace_module,
                     replace_subtrees)

__all__ = [
    "bert_config_from_hf", "extract_bert_encoder", "restore_bert_encoder",
    "gpt2_config_from_hf", "extract_gpt2_blocks", "restore_gpt2_blocks",
    "InjectionPolicy", "register_policy", "get_policy", "detect_policy",
    "registered_policies", "replace_module", "replace_subtrees",
]
