"""Training-health monitor: anomaly detection with provenance and the
hang watchdog.

Three failure modes the telemetry spine (per-step JSONL, roofline, MFU,
goodput) could not see:

- **A NaN/Inf surfaced only as an fp16 overflow-skip counter.** The
  step programs now carry an in-graph health tap — one ``[num_leaves]``
  f32 array of per-leaf gradient sum-of-squares (``leaf_sq_taps``) —
  that rides the existing ring buffer and syncs inside the drain's ONE
  batched ``device_get`` (zero added hot-path fences; the tap itself is
  one extra read of the grad tree, priced honestly in the docs). At
  drain time the tap gives provenance: the FIRST non-finite leaf (tree
  flatten order) and its top-level layer, for both non-finite loss and
  the fp16 overflow vote. Per-layer grad norms derive host-side from
  the same array (``TapSpec`` groups leaves by top-level key), so the
  in-graph cost stays one small array per step.

- **A loss/grad-norm spike drowned in the JSONL.** ``EwmaDetector``
  keeps an exponentially-weighted mean/variance per metric and flags
  ``|z| > z_threshold`` after a warmup count. Detection runs at drain
  time on the already-fetched host scalars — never on the hot path.

- **A hang produced silence.** ``HangWatchdog`` is a daemon thread fed
  two O(1) host-side signals: ``pending(name)`` when a step function
  dispatches and ``beat(wall_s)`` when a step completes. When no step
  completes within ``max(min_timeout_s, factor * p95(recent walls))``
  it fires ONCE (re-arming on the next beat): all-thread stacks via
  ``faulthandler.dump_traceback`` to a file, a ``memory_stats()``
  sample, and the pending step signature, delivered as a structured
  ``watchdog`` telemetry event.

Events flow through ``Telemetry.event`` into the JSONL stream, the
flight recorder (monitor/flight.py), and ``tools/telemetry_report.py``'s
``health`` section.
"""
from __future__ import annotations

import faulthandler
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import logger


# --------------------------------------------------------------------- #
# In-graph taps + provenance spec
# --------------------------------------------------------------------- #
class TapSpec:
    """Host-side decoder for the in-graph leaf tap: leaf paths in tree
    flatten order, each mapped to its top-level "layer" (first path
    component). Built ONCE from the params tree (host metadata only)."""

    def __init__(self, leaf_paths: List[str], layer_names: List[str],
                 leaf_layer_idx: List[int]):
        self.leaf_paths = list(leaf_paths)
        self.layer_names = list(layer_names)
        self.leaf_layer_idx = list(leaf_layer_idx)

    @classmethod
    def from_tree(cls, tree: Any) -> "TapSpec":
        import jax
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        leaf_paths, layers, idx = [], [], []
        for path, _leaf in flat:
            leaf_paths.append(jax.tree_util.keystr(path))
            top = str(path[0]) if path else "<root>"
            # keystr-style component without the container syntax noise.
            top = top.strip("[]'\".")
            if top not in layers:
                layers.append(top)
            idx.append(layers.index(top))
        return cls(leaf_paths, layers, idx)

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_paths)

    def layer_of(self, leaf_index: int) -> str:
        return self.layer_names[self.leaf_layer_idx[leaf_index]]

    def layer_norms(self, leaf_sq: np.ndarray) -> Dict[str, Any]:
        """Per-layer grad norms from the per-leaf sum-of-squares (host
        aggregation — the in-graph tap stays per-leaf). Non-finite
        values stringify so the JSONL stays parseable everywhere."""
        sums = np.zeros(len(self.layer_names), np.float64)
        bad = np.zeros(len(self.layer_names), bool)
        for i, s in enumerate(np.asarray(leaf_sq, np.float64)):
            j = self.leaf_layer_idx[i]
            if math.isfinite(float(s)):
                sums[j] += float(s)
            else:
                bad[j] = True
        out: Dict[str, Any] = {}
        for j, name in enumerate(self.layer_names):
            out[name] = "non-finite" if bad[j] \
                else round(float(np.sqrt(sums[j])), 6)
        return out


def leaf_sq_taps(grads: Any):
    """The in-graph tap: per-leaf sum of squares, f32, stacked into one
    ``[num_leaves]`` array (tree flatten order — TapSpec decodes it).
    Non-finite in any leaf => non-finite in its entry, which is exactly
    the fp16 overflow vote's information with provenance attached."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.stack([jnp.sum(jnp.square(x.astype(jnp.float32)))
                      for x in leaves])


# --------------------------------------------------------------------- #
# EWMA z-score spike detection
# --------------------------------------------------------------------- #
class EwmaDetector:
    """Exponentially-weighted mean/variance with z-score spike flagging.

    ``update(x)`` returns the z-score when ``|z| > z_threshold`` after
    ``warmup`` finite samples, else None. The baseline updates on every
    sample INCLUDING flagged ones (a level shift fires once and is then
    absorbed, instead of firing forever against a frozen baseline)."""

    def __init__(self, alpha: float = 0.1, z_threshold: float = 6.0,
                 warmup: int = 20):
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup)
        self.mean: Optional[float] = None
        self.var = 0.0
        self.n = 0

    @property
    def std(self) -> float:
        return math.sqrt(max(0.0, self.var))

    def update(self, x: float) -> Optional[float]:
        x = float(x)
        if not math.isfinite(x):
            return None   # non-finite is its own (provenance) event
        z: Optional[float] = None
        if self.mean is not None and self.n >= self.warmup:
            # Relative std floor: a dead-constant series (var == 0) must
            # not divide by zero, but a genuine jump off a flat baseline
            # SHOULD fire — with a huge z, which is the honest answer.
            denom = max(self.std, 1e-6 * max(1.0, abs(self.mean)))
            z0 = (x - self.mean) / denom
            if abs(z0) > self.z_threshold:
                z = z0
        if self.mean is None:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1
        return z


# --------------------------------------------------------------------- #
# Drain-time health monitor
# --------------------------------------------------------------------- #
class HealthMonitor:
    """Consumes drained (host-native) step records; emits anomaly event
    payloads. Owned by Telemetry; runs only at report boundaries."""

    def __init__(self, spec: Optional[TapSpec] = None,
                 z_threshold: float = 6.0, ewma_alpha: float = 0.1,
                 warmup_steps: int = 20, max_events: int = 256):
        self.spec = spec
        self.detectors = {
            "loss": EwmaDetector(ewma_alpha, z_threshold, warmup_steps),
            "grad_norm": EwmaDetector(ewma_alpha, z_threshold,
                                      warmup_steps),
        }
        self.counts: Dict[str, int] = {}
        self.anomalies: deque = deque(maxlen=int(max_events))

    def check_step(self, step: int, rec: Dict[str, Any],
                   leaf_sq: Optional[np.ndarray] = None
                   ) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        loss = rec.get("loss")
        if isinstance(loss, (int, float)) and not isinstance(loss, bool):
            if not math.isfinite(float(loss)):
                # `overflow` rides along: a non-finite value on an
                # overflow-SKIPPED step is routine fp16 loss-scale
                # mechanics (the update was discarded); unskipped is the
                # defect class the bench gate fails on.
                ev = {"anomaly": "nonfinite_loss", "anomaly_step": step,
                      "value": str(float(loss)),
                      "overflow": bool(rec.get("overflow", False))}
                ev.update(self._provenance(leaf_sq))
                events.append(ev)
            else:
                z = self.detectors["loss"].update(float(loss))
                if z is not None:
                    events.append(self._spike("loss", step, float(loss), z))
        gn = rec.get("grad_norm")
        overflow = bool(rec.get("overflow", False))
        gn_val = float(gn) if isinstance(gn, (int, float)) \
            and not isinstance(gn, bool) else None
        # The tap is a first-class detector, not just provenance: on the
        # fp32 no-clip path grad_norm is the -1 "not computed" sentinel
        # and there is no overflow vote, so a NaN gradient silently
        # poisons the params — only the per-leaf tap sees it.
        tap_bad = leaf_sq is not None and \
            not bool(np.isfinite(np.asarray(leaf_sq,
                                            np.float64)).all())
        if overflow or tap_bad or \
                (gn_val is not None and not math.isfinite(gn_val)):
            ev = {"anomaly": "nonfinite_grad", "anomaly_step": step,
                  "overflow": overflow}
            if gn_val is not None:
                ev["grad_norm"] = gn_val if math.isfinite(gn_val) \
                    else str(gn_val)
            ev.update(self._provenance(leaf_sq))
            events.append(ev)
        elif gn_val is not None and gn_val >= 0.0:
            # -1.0 is the engine's "norm not computed" sentinel.
            z = self.detectors["grad_norm"].update(gn_val)
            if z is not None:
                events.append(self._spike("grad_norm", step, gn_val, z))
        for ev in events:
            kind = ev["anomaly"]
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.anomalies.append(ev)
        return events

    def _spike(self, metric: str, step: int, value: float,
               z: float) -> Dict[str, Any]:
        det = self.detectors[metric]
        return {"anomaly": f"{metric}_spike", "anomaly_step": step,
                "metric": metric, "value": round(value, 6),
                "z": round(float(z), 3),
                "ewma_mean": round(float(det.mean), 6),
                "ewma_std": round(det.std, 6)}

    def _provenance(self, leaf_sq: Optional[np.ndarray]) -> Dict[str, Any]:
        """First-non-finite-leaf attribution from the in-graph tap."""
        if leaf_sq is None or self.spec is None:
            return {}
        arr = np.asarray(leaf_sq, np.float64).reshape(-1)
        if arr.shape[0] != self.spec.num_leaves:
            return {"tap_mismatch": [int(arr.shape[0]),
                                     self.spec.num_leaves]}
        bad = np.flatnonzero(~np.isfinite(arr))
        if bad.size == 0:
            # Overflow vote without a non-finite tap (e.g. a host-voted
            # sparse overflow): still report the layer norms for context.
            return {"layer_grad_norms": self.spec.layer_norms(arr)}
        i = int(bad[0])
        return {"first_nonfinite_leaf": self.spec.leaf_paths[i],
                "first_nonfinite_layer": self.spec.layer_of(i),
                "nonfinite_leaves": int(bad.size),
                "num_leaves": int(arr.shape[0]),
                "layer_grad_norms": self.spec.layer_norms(arr)}

    def summary(self) -> Dict[str, Any]:
        return {"counts": dict(self.counts),
                "total": int(sum(self.counts.values()))}


# --------------------------------------------------------------------- #
# Hang watchdog
# --------------------------------------------------------------------- #
class HangWatchdog:
    """Daemon thread that fires when no step completes within
    ``max(min_timeout_s, factor * p95(recent step walls))``.

    Hot-path cost: ``pending()`` is one attribute store at dispatch,
    ``beat()`` is a deque append + two stores at completion. The thread
    samples device memory and dumps stacks only when it FIRES."""

    def __init__(self, factor: float = 10.0, min_timeout_s: float = 120.0,
                 poll_s: Optional[float] = None,
                 on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
                 dump_dir: Optional[str] = None, window: int = 64,
                 memory_sampler: Optional[Callable] = None):
        self.factor = float(factor)
        self.min_timeout_s = float(min_timeout_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(0.05, self.min_timeout_s / 4.0)
        self.on_fire = on_fire
        self.dump_dir = dump_dir or "."
        self._walls: deque = deque(maxlen=int(window))
        self._last_beat = time.perf_counter()
        self._pending: Optional[str] = None
        self._armed = True
        self.fires = 0
        self.events: List[Dict[str, Any]] = []
        if memory_sampler is None:
            from .memory import device_memory_stats
            memory_sampler = device_memory_stats
        self._memory_sampler = memory_sampler
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -------------------------- hot path ----------------------------- #
    def pending(self, name: str) -> None:
        """A step function is dispatching — remember its signature so a
        fire can name what the run was stuck on."""
        self._pending = name

    def beat(self, wall_s: Optional[float] = None) -> None:
        """A step completed: record its wall, reset the clock, re-arm."""
        if wall_s is not None and wall_s > 0.0:
            self._walls.append(float(wall_s))
        self._last_beat = time.perf_counter()
        self._armed = True

    def disarm(self) -> None:
        """Stand down until the next beat(). For bounded-duration guards
        (the async checkpoint writer arms at write start and disarms at
        completion) where silence between work items is legitimate, not
        a hang."""
        self._armed = False

    # -------------------------- thread ------------------------------- #
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ds-hang-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.poll_s + 1.0)

    def _p95_wall(self) -> Optional[float]:
        """Nearest-rank p95 of the recent step walls (the ONE percentile
        rule the timeout and the fired event both report)."""
        if not self._walls:
            return None
        walls = sorted(self._walls)
        return walls[min(len(walls) - 1,
                         int(round(0.95 * (len(walls) - 1))))]

    def timeout_s(self) -> float:
        p95 = self._p95_wall()
        if p95 is None:
            return self.min_timeout_s
        return max(self.min_timeout_s, self.factor * p95)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            elapsed = time.perf_counter() - self._last_beat
            timeout = self.timeout_s()
            if self._armed and elapsed > timeout:
                self._armed = False   # once per stall; next beat re-arms
                self.fires += 1
                try:
                    event = self._fire(elapsed, timeout)
                except Exception as e:  # the watchdog must never kill
                    event = {"error": f"{type(e).__name__}: {e}"[:200],
                             "elapsed_s": round(elapsed, 3)}
                self.events.append(event)
                if self.on_fire is not None:
                    try:
                        self.on_fire(dict(event))
                    except Exception:
                        pass

    def _fire(self, elapsed: float, timeout: float) -> Dict[str, Any]:
        dump_path = os.path.join(self.dump_dir, "watchdog_stacks.txt")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(dump_path, "w") as f:
                f.write(f"# hang watchdog fire #{self.fires}: no step in "
                        f"{elapsed:.1f}s (timeout {timeout:.1f}s), "
                        f"pending={self._pending}\n")
                faulthandler.dump_traceback(file=f, all_threads=True)
        except Exception as e:
            dump_path = f"<dump failed: {type(e).__name__}: {e}>"
        mem = None
        try:
            mem = self._memory_sampler()
        except Exception:
            pass
        p95 = self._p95_wall()
        event = {
            "fire": self.fires,
            # No completed step yet => the run never got past warmup
            # (stuck compiling / first dispatch), a different diagnosis
            # than a steady-state hang.
            "phase": "steady" if self._walls else "startup",
            "pending_fn": self._pending,
            "elapsed_s": round(elapsed, 3),
            "timeout_s": round(timeout, 3),
            "p95_step_wall_s": round(p95, 4) if p95 is not None else None,
            "steps_observed": len(self._walls),
            "threads": threading.active_count(),
            "stack_dump_path": dump_path,
        }
        if isinstance(mem, dict):
            event["memory"] = {k: mem[k] for k in
                               ("bytes_in_use_max", "peak_bytes_in_use_max",
                                "num_devices") if k in mem}
        logger.warning(
            f"telemetry: hang watchdog fired — no step completed in "
            f"{elapsed:.1f}s (timeout {timeout:.1f}s, pending "
            f"{self._pending}); stacks dumped to {dump_path}")
        return event


__all__ = ["TapSpec", "leaf_sq_taps", "EwmaDetector", "HealthMonitor",
           "HangWatchdog"]
