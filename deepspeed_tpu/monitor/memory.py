"""Device-memory watermarks vs the analytic ZeRO model-state footprint.

``device_memory_stats`` samples ``memory_stats()`` across ALL local
devices (the single shared sampler — ``runtime/utils.see_memory_usage``
and the telemetry drain both use it), aggregating max and sum per field.
It runs only at report boundaries: each sample is a host API call per
device, cheap but not free, and watermark math never belongs on the hot
path.

``analytic_state_bytes`` prices the engine state's per-device HBM from
sharding METADATA alone (``sharding.shard_shape``): for each leaf, the
bytes of one device's shard. Under ZeRO the optimizer moments are
dp-sharded, so the analytic footprint is params + state/dp + scalars —
the memory story the sharding declarations promise. A measured peak far
above it (``peak > analytic * ratio + slack``; the slack absorbs
activations, XLA workspace, and allocator rounding) means the promise
broke — e.g. a regression replicating the moments — and surfaces as a
structured ``memory_watermark`` event instead of a silent OOM three
models later.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

_AGG_FIELDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(devices=None) -> Optional[Dict[str, Any]]:
    """Aggregate ``memory_stats()`` over local devices: per-device list
    plus ``<field>_max``/``<field>_sum`` for bytes_in_use /
    peak_bytes_in_use / bytes_limit. Returns None when no device reports
    stats (e.g. the CPU backend)."""
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            # Backend init failure degrades to "stats unavailable", the
            # contract see_memory_usage has always had.
            return None
    per: List[Dict[str, Any]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        per.append({"device": getattr(d, "id", len(per)),
                    **{f: int(stats.get(f, 0)) for f in _AGG_FIELDS}})
    if not per:
        return None
    out: Dict[str, Any] = {"num_devices": len(per), "per_device": per}
    for f in _AGG_FIELDS:
        vals = [p[f] for p in per]
        out[f"{f}_max"] = max(vals)
        out[f"{f}_sum"] = sum(vals)
    return out


def analytic_state_bytes(tree: Any, gather_working_set: int = 0) -> int:
    """Per-device bytes of ``tree`` (max across devices, from sharding
    metadata — no device access). Unsharded/unaddressable leaves count
    their full size.

    Each leaf is priced at ITS OWN sharding's shard shape, so ZeRO-3's
    dp-sharded parameters contribute params/dp — the true per-device
    footprint, never the replicated-param figure. ``gather_working_set``
    adds the stage-3 transient gather bound (compute-dtype gathered
    leaves live during the step: ``zero/stage3.gather_working_set_bytes``)
    so the watermark threshold and the telemetry_report memory section
    compare the measured peak against what a healthy stage-3 step
    actually holds, not just the resident state."""
    import jax
    import numpy as np
    total = int(gather_working_set)
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        itemsize = np.dtype(dtype).itemsize
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass
        n = itemsize
        for d in shape:
            n *= int(d)
        total += n
    return total


class MemoryWatermark:
    """Report-boundary watermark check against an analytic footprint."""

    def __init__(self, analytic_bytes: int, ratio: float = 2.0,
                 slack_bytes: int = 256 * 2 ** 20,
                 sampler: Callable[[], Optional[Dict[str, Any]]]
                 = device_memory_stats):
        self.analytic_bytes = int(analytic_bytes)
        self.ratio = float(ratio)
        self.slack_bytes = int(slack_bytes)
        self.sampler = sampler
        self.events: List[Dict[str, Any]] = []

    @property
    def threshold_bytes(self) -> int:
        return int(self.analytic_bytes * self.ratio) + self.slack_bytes

    def check(self):
        """Sample and compare. Returns ``(stats_or_None, event_or_None)``;
        the event is also appended to ``self.events``."""
        stats = self.sampler()
        if stats is None:
            return None, None
        peak = int(stats.get("peak_bytes_in_use_max", 0))
        if peak <= self.threshold_bytes:
            return stats, None
        event = {
            "peak_bytes_in_use_max": peak,
            "analytic_state_bytes": self.analytic_bytes,
            "threshold_bytes": self.threshold_bytes,
            "ratio": round(peak / max(1, self.analytic_bytes), 3),
            "watermark_ratio": self.ratio,
            "watermark_slack_bytes": self.slack_bytes,
        }
        self.events.append(event)
        return stats, event
