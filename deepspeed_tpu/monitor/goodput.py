"""Goodput ledger: attribute every wall-clock second between report
boundaries to a named bucket.

Throughput says how fast the useful steps were; goodput says where the
REST of the wall-clock went. The ledger runs on host-side monotonic
clocks only (no device syncs — the telemetry invariant), accumulating
in-window costs as they are measured and settling the window at each
drain:

- ``useful_compute`` — host wall spent inside non-overflow train steps,
  minus the stalls measured inside them. On the jitted paths this is
  dispatch wall (steps pipeline asynchronously); the roofline/MFU side
  covers device occupancy. On the host-synchronous offload path it is
  true step wall.
- ``data_stall``   — time the engine waited on ``next(data_iter)`` (new
  dataloader fetch-wait instrumentation).
- ``recompile``    — wall of jit cache-miss calls (trace+compile), from
  the recompile sentinel's per-miss clock. Cold-start compiles count
  too: they are real lost wall-clock in their window.
- ``overflow_skipped`` — wall of steps whose dynamic-loss-scale
  overflow check voted to skip the update (work executed, result
  discarded), minus any stall/compile wall measured inside those steps
  — that time is reattributed to its own bucket (a step can both
  cold-compile and overflow; the seconds are counted once).
- ``checkpoint``   — EXPOSED checkpoint wall (outermost checkpoint span
  only): sync save/load wall, or under async checkpointing the
  snapshot fetch + any blocking wait on the writer. Two sub-figures
  ride along without joining the bucket sum: ``checkpoint_snapshot_s``
  (the snapshot-phase subset of the exposed bucket) and
  ``checkpoint_write_bg_s`` (the BACKGROUND writer's wall — measured on
  its own thread, overlapping useful compute, so charging it against
  the window would double-count the same seconds).
- ``offload_exposed`` — ZeRO-Offload host time NOT hidden behind device
  work (step wall minus the device-only phase).
- ``other``        — the residual: window wall minus everything above
  (engine init in the first window, user code between steps, drain
  work). The ledger never invents time: buckets are measured
  independently, so a NEGATIVE residual means double-attribution and is
  surfaced, not clamped — and the "sums to window wall within 1%"
  acceptance gate is a real check on the measured buckets, not a
  tautology.

Windows are contiguous: a window closes at drain time and the next one
opens at the same instant, so no second is silently outside all windows.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

BUCKETS = ("useful_compute", "data_stall", "recompile", "overflow_skipped",
           "checkpoint", "offload_exposed", "other")

# (wall_s, overflow, offload_exposed_s) for one drained step record.
StepInfo = Tuple[float, bool, float]


class GoodputLedger:
    """Window-scoped wall-clock attribution (host clocks only)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.window_t0: float = clock()
        self._noted: Dict[str, float] = {"data_stall": 0.0,
                                         "recompile": 0.0,
                                         "checkpoint": 0.0}
        # Sub-figures: named subsets of a bucket (checkpoint_snapshot
        # within checkpoint). Reported per window, never summed into the
        # bucket total a second time.
        self._sub: Dict[str, float] = {}
        # Background seconds measured on other threads (the checkpoint
        # writer): overlap the window, reported but not charged.
        self._bg: Dict[str, float] = {}
        self._bg_lock = threading.Lock()
        self.windows_closed = 0
        self.totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.sub_totals: Dict[str, float] = {}
        self.bg_totals: Dict[str, float] = {}
        self.total_window_s = 0.0

    # ------------------------------------------------------------------ #
    # In-window accumulation (hot-path safe: float adds)
    # ------------------------------------------------------------------ #
    def note(self, bucket: str, seconds: float,
             sub: Optional[str] = None) -> None:
        """Record directly-measured seconds for ``data_stall`` /
        ``recompile`` / ``checkpoint`` as they happen. ``sub`` names a
        reported-only subset of the bucket (``checkpoint_snapshot``)."""
        if seconds > 0.0:
            self._noted[bucket] = self._noted.get(bucket, 0.0) + seconds
            if sub is not None:
                self._sub[sub] = self._sub.get(sub, 0.0) + seconds

    def note_background(self, key: str, seconds: float) -> None:
        """Record seconds measured on a BACKGROUND thread (the async
        checkpoint writer). Reported as ``<key>_bg_s`` per window,
        excluded from the bucket sum — those seconds overlap the window
        and charging them would double-count the wall."""
        if seconds > 0.0:
            with self._bg_lock:
                self._bg[key] = self._bg.get(key, 0.0) + seconds

    def has_pending(self) -> bool:
        """True when directly-measured seconds await settlement — e.g. a
        checkpoint saved after the last report boundary. close() checks
        this so trailing attributed time is never silently dropped."""
        if any(v > 0.0 for v in self._noted.values()):
            return True
        with self._bg_lock:
            return any(v > 0.0 for v in self._bg.values())

    def peek(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Non-destructive view of the OPEN window (the flight
        recorder's "unsettled goodput window"): elapsed wall since the
        last settlement plus the directly-measured buckets noted so far.
        Settlement math (residual, consistency) only happens at
        close_window — this is the raw evidence, not a verdict."""
        now = self._clock() if now is None else now
        with self._bg_lock:
            bg = {k: round(v, 6) for k, v in self._bg.items()}
        return {
            "open_window_s": round(max(0.0, now - self.window_t0), 6),
            "noted_s": {k: round(v, 6) for k, v in self._noted.items()},
            "background_s": bg,
            "windows_closed": self.windows_closed,
        }

    # ------------------------------------------------------------------ #
    # Window settlement (report-boundary work)
    # ------------------------------------------------------------------ #
    def close_window(self, steps: Iterable[StepInfo],
                     now: Optional[float] = None) -> Dict[str, Any]:
        """Settle the current window against the drained step records and
        open the next one. Returns the JSONL-ready ledger dict."""
        now = self._clock() if now is None else now
        window_s = max(0.0, now - self.window_t0)
        step_list: List[StepInfo] = list(steps)

        overflow_s = sum(w for w, o, _ in step_list if o)
        exposed_s = sum(e for w, o, e in step_list if not o)
        in_step_s = sum(w for w, o, _ in step_list if not o)
        data_stall = self._noted.get("data_stall", 0.0)
        recompile = self._noted.get("recompile", 0.0)
        checkpoint = self._noted.get("checkpoint", 0.0)
        # Stalls measured inside train_batch are part of the per-step
        # wall; useful compute is what remains of the non-overflow steps.
        useful = in_step_s - data_stall - recompile - exposed_s
        if useful < 0.0:
            # The excess stall/compile wall was measured inside OVERFLOW
            # steps (e.g. the first step cold-compiles AND overflows
            # under a high initial loss scale): those seconds belong to
            # data_stall/recompile — the more actionable attribution —
            # so move them out of the overflow bucket instead of
            # double-counting. overflow_s going negative here is the
            # genuine double-attribution signal (checked below).
            overflow_s += useful
            useful = 0.0
        buckets = {
            "useful_compute": useful,
            "data_stall": data_stall,
            "recompile": recompile,
            "overflow_skipped": overflow_s,
            "checkpoint": checkpoint,
            "offload_exposed": exposed_s,
        }
        other = window_s - sum(buckets.values())
        buckets["other"] = other

        sub = self._sub
        self._sub = {}
        with self._bg_lock:
            bg = self._bg
            self._bg = {}
        self._noted = {"data_stall": 0.0, "recompile": 0.0,
                       "checkpoint": 0.0}
        self.window_t0 = now
        self.windows_closed += 1
        for b in BUCKETS:
            self.totals[b] += buckets[b]
        for k, v in sub.items():
            self.sub_totals[k] = self.sub_totals.get(k, 0.0) + v
        for k, v in bg.items():
            self.bg_totals[k] = self.bg_totals.get(k, 0.0) + v
        self.total_window_s += window_s

        out: Dict[str, Any] = {"window_s": round(window_s, 6),
                               "steps": len(step_list)}
        out.update({f"{b}_s": round(buckets[b], 6) for b in BUCKETS})
        # Reported-only figures: subsets of a bucket and background
        # (overlapped) seconds — OUTSIDE the sum the accounted-fraction
        # check covers, by design.
        out.update({f"{k}_s": round(v, 6) for k, v in sub.items()})
        out.update({f"{k}_bg_s": round(v, 6) for k, v in bg.items()})
        # Sum check the acceptance gate reads: measured buckets + residual
        # vs window wall. A healthy run keeps overflow and the residual
        # non-negative; double-attribution shows up as either < 0.
        out["accounted_fraction"] = round(
            sum(buckets.values()) / window_s, 6) if window_s > 0 else 1.0
        out["consistent"] = bool(
            overflow_s >= -0.01 * window_s and other >= -0.01 * window_s)
        return out

    # ------------------------------------------------------------------ #
    def summary(self) -> Dict[str, Any]:
        """Run-cumulative bucket totals and the goodput fraction."""
        total = self.total_window_s
        out: Dict[str, Any] = {
            "windows": self.windows_closed,
            "total_window_s": round(total, 6),
        }
        out.update({f"{b}_s": round(self.totals[b], 6) for b in BUCKETS})
        out.update({f"{k}_s": round(v, 6)
                    for k, v in self.sub_totals.items()})
        out.update({f"{k}_bg_s": round(v, 6)
                    for k, v in self.bg_totals.items()})
        out["goodput_fraction"] = round(
            self.totals["useful_compute"] / total, 6) if total > 0 else 0.0
        if total > 0:
            # The headline the resilience gate reads: how much of the
            # wall the run actually PAID for checkpointing (background
            # write wall is excluded — it overlapped).
            out["checkpoint_exposed_share"] = round(
                self.totals["checkpoint"] / total, 6)
        return out


def extract_step_info(rec: Dict[str, Any]) -> StepInfo:
    """StepInfo from a drained (post-fetch, host-native) step record."""
    wall_s = float(rec.get("wall_ms", 0.0)) / 1e3
    overflow = bool(rec.get("overflow", False))
    exposed_s = 0.0
    off = rec.get("offload")
    if isinstance(off, dict):
        off_wall = float(off.get("wall_ms", 0.0))
        dev = float(off.get("device_step_ms", 0.0))
        if off_wall > 0.0 and dev > 0.0:
            exposed_s = max(0.0, off_wall - dev) / 1e3
    return (wall_s, overflow, exposed_s)


__all__ = ["GoodputLedger", "BUCKETS", "extract_step_info"]
