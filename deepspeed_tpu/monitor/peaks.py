"""Chip peak table — the ONE place hardware ceilings live.

``bench.py`` used to own a private ``TPU_PEAK_TFLOPS`` dict for its
utilisation denominator; the roofline cost model (cost_model.py), the
environment report, and the bench gate all need the same numbers, so the
table lives here and everyone imports it.

The figures are rough public per-chip specs by TPU generation:

- ``bf16_tflops``: dense bf16/int8-class matmul peak (the MXU ceiling and
  the MFU denominator);
- ``hbm_gbs``: HBM bandwidth, GB/s (the memory-roofline ceiling);
- ``ici_gbs``: aggregate inter-chip interconnect bandwidth per chip, GB/s
  one-way (the communication-roofline ceiling for ring collectives
  WITHIN one slice).
- ``dcn_gbs``: per-chip share of the host's data-center-network NIC,
  GB/s one-way — the SECOND communication tier, what inter-slice
  collectives ride in a multislice deployment. These are rough
  deployment-dependent figures (host NIC bandwidth divided by chips per
  host), one to two orders of magnitude below ICI — which is the whole
  point of the hierarchical sync: the two tiers must be priced
  separately or the roofline lies (a step can be DCN-bound while ICI
  idles).

They are CEILINGS for roofline verdicts and utilisation fractions, not
measurements — real programs see lower effective bandwidth (stride
patterns, link contention), and the DCN column doubly so (it depends on
the NIC provisioning of the actual pod). On non-TPU backends (CPU dev
meshes) there is no meaningful peak; ``chip_peaks()`` returns the v5e
row flagged ``assumed=True`` so downstream math stays total-ordered and
every consumer can say "vs an ASSUMED v5e peak" instead of crashing or
silently printing garbage.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional


# Rough bf16 peak TFLOPs per chip by TPU generation (public figures);
# the utilisation denominator (lifted from bench.py, now shared).
TPU_PEAK_TFLOPS: Dict[str, float] = {
    "v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
}

# HBM bandwidth GB/s per chip (public figures, same generations).
TPU_HBM_GBS: Dict[str, float] = {
    "v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0,
}

# Aggregate one-way ICI bandwidth GB/s per chip (public per-chip
# interconnect figures: 2400/1600/4800/3584 Gbps).
TPU_ICI_GBS: Dict[str, float] = {
    "v4": 300.0, "v5e": 200.0, "v5p": 600.0, "v6e": 448.0,
}

# Per-chip share of the host DCN NIC, GB/s one-way: rough figures from
# ~100-200 Gbps host NICs over 4-8 chips per host (deployment-dependent
# — these are two-tier-roofline ceilings for the inter-slice hop, not
# specs; a real pod's provisioning should overwrite the verdict with a
# measured figure). Note the ratio to ICI: 30-60x slower per chip.
TPU_DCN_GBS: Dict[str, float] = {
    "v4": 6.25, "v5e": 6.25, "v5p": 12.5, "v6e": 12.5,
}

_DEFAULT_GEN = "v5e"


@dataclasses.dataclass(frozen=True)
class ChipPeaks:
    """Per-chip hardware ceilings for one device generation."""
    name: str                  # resolved generation key, e.g. "v5e"
    bf16_tflops: float
    hbm_gbs: float
    ici_gbs: float
    dcn_gbs: float = TPU_DCN_GBS["v5e"]
    assumed: bool = False      # True when the device kind had no table row

    @property
    def flops_per_sec(self) -> float:
        return self.bf16_tflops * 1e12

    @property
    def hbm_bytes_per_sec(self) -> float:
        return self.hbm_gbs * 1e9

    @property
    def ici_bytes_per_sec(self) -> float:
        return self.ici_gbs * 1e9

    @property
    def dcn_bytes_per_sec(self) -> float:
        return self.dcn_gbs * 1e9

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def _resolve_gen(device_kind: str) -> Optional[str]:
    kind = (device_kind or "").lower()
    for key in TPU_PEAK_TFLOPS:
        if key in kind:
            return key
    return None


def peaks_for_kind(device_kind: str) -> ChipPeaks:
    """ChipPeaks for a device-kind string; unknown kinds (CPU, GPU, future
    TPUs) get the v5e row flagged ``assumed``."""
    gen = _resolve_gen(device_kind)
    key, assumed = (gen, False) if gen else (_DEFAULT_GEN, True)
    return ChipPeaks(name=key, bf16_tflops=TPU_PEAK_TFLOPS[key],
                     hbm_gbs=TPU_HBM_GBS[key], ici_gbs=TPU_ICI_GBS[key],
                     dcn_gbs=TPU_DCN_GBS[key], assumed=assumed)


def chip_peaks(device=None) -> ChipPeaks:
    """ChipPeaks of ``device`` (default: the first visible device)."""
    if device is None:
        import jax
        device = jax.devices()[0]
    return peaks_for_kind(getattr(device, "device_kind", ""))


def chip_peak_tflops() -> float:
    """bf16 peak TFLOPs of the first visible chip (bench.py's historical
    API: defaults to v5e when the kind is unknown; CPU runs report vs
    that assumed peak too)."""
    return chip_peaks().bf16_tflops


__all__ = ["TPU_PEAK_TFLOPS", "TPU_HBM_GBS", "TPU_ICI_GBS", "TPU_DCN_GBS",
           "ChipPeaks", "peaks_for_kind", "chip_peaks", "chip_peak_tflops"]
