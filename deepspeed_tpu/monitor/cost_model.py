"""Roofline cost model over the engine's compiled step functions.

The telemetry spine (PR 4) already knows every compiled step path: the
recompile sentinel wraps train/offload/sparse/grad/apply and records, on
each compile, the abstract argument signature (shapes + dtypes +
shardings — host metadata that survives buffer donation). This module
fuses three existing sources into per-path roofline verdicts:

1. **XLA's own compiled cost analysis** — each registered path is
   AOT-lowered from its recorded abstract signature and
   ``Compiled.cost_analysis()`` supplies optimized-HLO flops and bytes
   accessed. For an SPMD-partitioned program these are PER-DEVICE
   figures (the analysis runs on the partitioned module).
2. **The jaxpr-walk flops profiler** (profiling/flops_profiler) — the
   analytic GLOBAL flops count. Crucially it multiplies ``scan`` bodies
   by their trip count, which XLA's cost analysis does NOT (a while/scan
   body is costed once — the known undercount for ``scan_layers`` models
   and gas>1 accumulation loops). The two counters cross-validate each
   other on straight-line programs (a tier-1 gate pins the gpt2 block
   within tolerance) and the analytic count scan-corrects the XLA one.
3. **The PR-3 interconnect wire model** — per-step gradient-sync bytes
   at the engine's RESOLVED lowering.

Per path the model prices FOUR ceilings against the shared chip-peak
table (peaks.py) — the interconnect is two-tier since multi-slice
landed:

    t_compute = flops_per_device / bf16_peak
    t_hbm     = hbm_bytes_per_device / hbm_bandwidth
    t_comm    = ici_wire_bytes / ici_bandwidth      (in-slice tier)
    t_dcn     = dcn_wire_bytes / dcn_bandwidth      (inter-slice tier)

and the verdict is the binding ceiling; ``max`` of the four is the
analytic step-time floor (perfect-overlap roofline). The tiers are
priced separately because their ceilings differ by 1-2 orders of
magnitude: a multislice step can be DCN-bound while ICI idles, and one
fused "comm" figure would hide exactly that. MFU follows the same
table: achieved flops/sec per device over the bf16 peak.

Everything here is REPORT-BOUNDARY work: building the model AOT-compiles
each path once (host-side compile, no device traffic, no fences), so the
zero-added-hot-path-syncs invariant holds by construction.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .peaks import ChipPeaks, chip_peaks

BOUND_COMPUTE = "compute"
BOUND_HBM = "hbm"
BOUND_INTERCONNECT = "interconnect"
BOUND_DCN = "dcn"

# analytic/XLA flops ratio above which the XLA figures are treated as a
# scan undercount and scaled (a straight-line program sits near 1.0; a
# scanned one sits near the trip count).
_SCAN_DETECT_RATIO = 1.5


def abstract_leaf(x: Any) -> Any:
    """ShapeDtypeStruct mirror of an array leaf (keeps the sharding so an
    AOT lower partitions exactly like the live call); non-array leaves
    pass through. Works on donated/deleted arrays — aval metadata
    outlives the buffers."""
    import jax
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x
    sharding = getattr(x, "sharding", None)
    # An UNCOMMITTED array's SingleDeviceSharding is placement history,
    # not a constraint — mirroring it would pin the AOT lower to that one
    # device and clash with mesh-sharded siblings ("incompatible devices
    # for jitted computation" on the offload grad path, whose rng rides
    # along uncommitted). Drop it; jax re-defaults placement at lower.
    if isinstance(sharding, jax.sharding.SingleDeviceSharding):
        sharding = None
    try:
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)


def abstract_args_of(args: Tuple, kwargs: Dict) -> Tuple[Tuple, Dict]:
    import jax
    return jax.tree_util.tree_map(abstract_leaf, (tuple(args), dict(kwargs)))


def xla_cost_analysis(fn: Callable, abstract_args: Tuple,
                      abstract_kwargs: Dict) -> Optional[Dict[str, float]]:
    """{"flops", "bytes_accessed"} from ``Compiled.cost_analysis()`` of an
    AOT lower at the recorded abstract signature; None when the backend
    or jax version cannot supply it. Handles both historical return
    shapes (list-of-dict and plain dict)."""
    try:
        compiled = fn.lower(*abstract_args, **abstract_kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not ca:
            return None
        return {"flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    except Exception:
        return None


def analytic_profile(fn: Callable, abstract_args: Tuple,
                     abstract_kwargs: Dict
                     ) -> Optional[Tuple[int, List[Dict[str, Any]]]]:
    """One jaxpr-walk of the program: (GLOBAL flops — scan bodies
    multiplied by trip count — and the top-module breakdown). None when
    the trace fails. ONE walk serves both consumers: the roofline total
    and the per-path "where do the flops go" detail (the pipeline
    engine's per-stage section reads it instead of re-walking)."""
    try:
        from ..profiling.flops_profiler import profile_fn
        if abstract_kwargs:
            fn = _bind_kwargs(fn, abstract_kwargs)
        res = profile_fn(fn, *abstract_args, run=False)
        top = [{"module": name, "flops": int(f)}
               for name, f, _ in res.top_modules(5, depth=1)]
        return int(res.total_flops), top
    except Exception:
        return None


def analytic_flops(fn: Callable, abstract_args: Tuple,
                   abstract_kwargs: Dict) -> Optional[int]:
    """GLOBAL flops of one invocation via the jaxpr-walk profiler (scan
    bodies multiplied by trip count). None when the trace fails."""
    prof = analytic_profile(fn, abstract_args, abstract_kwargs)
    return None if prof is None else prof[0]


def _bind_kwargs(fn: Callable, kwargs: Dict) -> Callable:
    def bound(*args):
        return fn(*args, **kwargs)
    return bound


def roofline(flops_per_device: float, hbm_bytes_per_device: float,
             comm_bytes: float, peaks: ChipPeaks,
             dcn_bytes: float = 0.0) -> Dict[str, Any]:
    """Roofline verdict for one path: which ceiling binds, and the
    perfect-overlap analytic time floor. ``comm_bytes`` is the ICI
    (in-slice) tier; ``dcn_bytes`` the inter-slice tier (0 on
    single-slice meshes — the pre-multislice behavior exactly)."""
    t_compute = flops_per_device / peaks.flops_per_sec
    t_hbm = hbm_bytes_per_device / peaks.hbm_bytes_per_sec
    t_comm = comm_bytes / peaks.ici_bytes_per_sec
    t_dcn = dcn_bytes / peaks.dcn_bytes_per_sec
    times = {BOUND_COMPUTE: t_compute, BOUND_HBM: t_hbm,
             BOUND_INTERCONNECT: t_comm, BOUND_DCN: t_dcn}
    bound = max(times, key=times.get)
    return {
        "t_compute_ms": t_compute * 1e3,
        "t_hbm_ms": t_hbm * 1e3,
        "t_comm_ms": t_comm * 1e3,
        "t_dcn_ms": t_dcn * 1e3,
        "bound": bound,
        "floor_ms": times[bound] * 1e3,
        # operational intensity (flops/byte) vs the machine balance point
        # — the classic roofline x-axis, for plotting/debugging.
        "intensity_flops_per_byte":
            flops_per_device / max(1.0, hbm_bytes_per_device),
        "machine_balance_flops_per_byte":
            peaks.flops_per_sec / peaks.hbm_bytes_per_sec,
    }


def path_cost(name: str, fn: Callable, abstract_args: Tuple,
              abstract_kwargs: Dict, comm_bytes: float, n_devices: int,
              peaks: ChipPeaks, dcn_bytes: float = 0.0) -> Dict[str, Any]:
    """Fused per-path cost record: XLA + analytic counters, scan
    correction, roofline verdict."""
    xla = xla_cost_analysis(fn, abstract_args, abstract_kwargs)
    prof = analytic_profile(fn, abstract_args, abstract_kwargs)
    analytic = prof[0] if prof is not None else None
    entry: Dict[str, Any] = {
        "path": name,
        "xla_available": xla is not None,
        "analytic_flops": analytic,
        "comm_bytes": int(comm_bytes),
        "dcn_bytes": int(dcn_bytes),
    }
    if prof is not None and prof[1]:
        entry["top_modules"] = prof[1]
    if xla is not None:
        entry["xla_flops_per_device"] = xla["flops"]
        entry["xla_bytes_per_device"] = xla["bytes_accessed"]

    # Best flops estimate per device: analytic (scan-aware, global) split
    # over devices; fall back to XLA's per-device figure.
    if analytic is not None and n_devices > 0:
        flops_dev = analytic / n_devices
    elif xla is not None:
        flops_dev = xla["flops"]
    else:
        entry["available"] = False
        return entry
    entry["flops_per_device"] = flops_dev

    # HBM bytes: XLA's count, scan-corrected when the analytic/XLA flops
    # ratio says the program loops (scan bodies are costed once by XLA —
    # bytes undercount by the same trip factor as flops, approximately).
    scan_scale = 1.0
    if xla is not None and xla["flops"] > 0 and analytic is not None:
        ratio = flops_dev / xla["flops"]
        if ratio > _SCAN_DETECT_RATIO:
            scan_scale = ratio
    entry["scan_scale"] = round(scan_scale, 3)
    hbm_bytes = (xla["bytes_accessed"] * scan_scale) if xla is not None \
        else 0.0
    entry["hbm_bytes_per_device"] = hbm_bytes

    entry.update(roofline(flops_dev, hbm_bytes, comm_bytes, peaks,
                          dcn_bytes=dcn_bytes))
    entry["available"] = True
    return entry


def mfu(flops_per_step_total: float, step_time_s: float, n_devices: int,
        peaks: ChipPeaks) -> float:
    """Model-FLOPs-utilisation-style fraction: achieved flops/sec per
    device over the chip's bf16 peak. The numerator is whatever flops
    count the caller trusts for one step — the analytic jaxpr-walk count
    here, which includes remat recompute when remat is on (an HFU-style
    figure then; equal to MFU with remat off)."""
    if step_time_s <= 0 or n_devices <= 0:
        return 0.0
    return flops_per_step_total / n_devices / step_time_s / \
        peaks.flops_per_sec


def build_cost_model(sentinel, comm_bytes_by_path: Dict[str, float],
                     step_paths: Dict[str, float], n_devices: int,
                     peaks: Optional[ChipPeaks] = None,
                     extra_paths: Optional[Dict[str, Tuple]] = None,
                     dcn_bytes_by_path: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """The engine-facing entry point.

    - ``sentinel``: the RecompileSentinel whose registry holds every
      compiled step function with its recorded abstract signature.
    - ``comm_bytes_by_path``: per-step ICI wire-model bytes attributed
      to each path (paths absent here price comm at 0).
    - ``dcn_bytes_by_path``: the inter-slice (DCN) tier, priced against
      its own bandwidth ceiling — empty/absent on single-slice meshes.
    - ``step_paths``: {path_name: invocations_per_train_step} — which
      registered paths compose ONE optimizer step (e.g. the trio path
      runs grad_step gas× then apply_grads once).
    - ``extra_paths``: {name: (fn, abstract_args, abstract_kwargs)} for
      paths not registered with the sentinel.

    Returns the JSONL-ready payload: per-path cost records, the fused
    per-step totals (flops, analytic floor, binding ceiling), and the
    peak table used.
    """
    peaks = peaks or chip_peaks()
    t_build0 = time.perf_counter()
    paths: Dict[str, Dict[str, Any]] = {}
    # The sentinel's formal registry handoff (shared with the lint
    # auditor).
    sources: Dict[str, Tuple] = dict(sentinel.registered_paths())
    for name, triple in (extra_paths or {}).items():
        sources.setdefault(name, triple)
    for name, (fn, a_args, a_kwargs) in sources.items():
        paths[name] = path_cost(name, fn, a_args, a_kwargs,
                                comm_bytes_by_path.get(name, 0.0),
                                n_devices, peaks,
                                dcn_bytes=(dcn_bytes_by_path or {})
                                .get(name, 0.0))

    # Fuse the paths that make up one optimizer step. Floors add across
    # sequentially-invoked programs (each path's internal ceilings can
    # overlap; distinct XLA programs cannot).
    step_flops = 0.0
    step_floor_ms = 0.0
    ceiling_ms = {BOUND_COMPUTE: 0.0, BOUND_HBM: 0.0,
                  BOUND_INTERCONNECT: 0.0, BOUND_DCN: 0.0}
    _ceiling_key = {BOUND_COMPUTE: "t_compute_ms", BOUND_HBM: "t_hbm_ms",
                    BOUND_INTERCONNECT: "t_comm_ms", BOUND_DCN: "t_dcn_ms"}
    missing: List[str] = []
    for name, weight in step_paths.items():
        p = paths.get(name)
        if p is None or not p.get("available"):
            missing.append(name)
            continue
        w = float(weight)
        if p.get("analytic_flops") is not None:
            step_flops += p["analytic_flops"] * w
        else:
            step_flops += p["flops_per_device"] * n_devices * w
        step_floor_ms += p["floor_ms"] * w
        for k in ceiling_ms:
            ceiling_ms[k] += p.get(_ceiling_key[k], 0.0) * w
    step_bound = max(ceiling_ms, key=ceiling_ms.get) if step_floor_ms else None
    return {
        "chip": peaks.as_dict(),
        "n_devices": int(n_devices),
        "paths": paths,
        "step": {
            "paths": {k: float(v) for k, v in step_paths.items()},
            "flops_per_step": step_flops,
            "floor_ms": round(step_floor_ms, 6),
            "bound": step_bound,
            "missing_paths": missing,
        },
        "build_seconds": round(time.perf_counter() - t_build0, 3),
    }


__all__ = ["build_cost_model", "path_cost", "roofline", "mfu",
           "xla_cost_analysis", "analytic_flops", "analytic_profile",
           "abstract_args_of",
           "BOUND_COMPUTE", "BOUND_HBM", "BOUND_INTERCONNECT",
           "BOUND_DCN"]
