"""Recompile sentinel: count jit cache misses on the engine's compiled
step functions and flag retraces after warmup.

On TPU an unexpected XLA recompile is a silent performance killer — a
shape-drifting batch or a host-rebuilt closure turns a single compiled
program into a compile-per-step treadmill, and nothing in the training
loop says so. The sentinel wraps each jitted step function:

- every call computes the ABSTRACT SIGNATURE of the arguments (treedef +
  per-leaf shape/dtype) — pure host metadata, no device sync;
- a cache miss is detected via the jitted function's ``_cache_size()``
  (growth across the call == a compile happened), falling back to
  signature-set membership when that private API is absent;
- the first ``warmup_calls`` compiles per function are expected (cold
  start); any later miss emits a structured event naming the function and
  the signature delta vs the previous call, and raises ``RecompileError``
  when ``telemetry.fail_on_recompile`` is set.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class RecompileError(RuntimeError):
    """Raised on a post-warmup jit cache miss under fail_on_recompile."""


def _leaf_desc(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    return f"py:{type(x).__name__}"


def abstract_signature(tree: Any) -> Tuple[Any, Tuple[Tuple[str, str], ...]]:
    """(hashable key, [(path, desc)]) for an argument pytree — host-side
    metadata only, never forces device values."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    descs = tuple((jax.tree_util.keystr(path), _leaf_desc(leaf))
                  for path, leaf in flat)
    return (treedef, tuple(d for _, d in descs)), descs


def signature_delta(old: Tuple[Tuple[str, str], ...],
                    new: Tuple[Tuple[str, str], ...]) -> List[str]:
    """Human-readable per-path differences between two signatures."""
    if tuple(old) == tuple(new):
        # The cache missed with an unchanged abstract signature: the
        # compiler keyed on something shapes/dtypes can't see (input
        # sharding/layout/committedness, donation state). One such miss is
        # expected when the donated first output becomes the second input
        # — that's inside the default warmup; repeated ones are real.
        return ["no abstract-signature change (input sharding/layout or "
                "donation-state change)"]
    o, n = dict(old), dict(new)
    out = []
    for path in n:
        if path not in o:
            out.append(f"{path}: added {n[path]}")
        elif o[path] != n[path]:
            out.append(f"{path}: {o[path]} -> {n[path]}")
    for path in o:
        if path not in n:
            out.append(f"{path}: removed {o[path]}")
    if not out:
        out.append("tree structure changed (same leaf signatures)")
    return out


class RecompileSentinel:
    """Per-engine registry of instrumented step functions."""

    def __init__(self, warmup_calls: int = 1, fail_on_recompile: bool = False,
                 on_event: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.warmup_calls = max(0, int(warmup_calls))
        self.fail_on_recompile = bool(fail_on_recompile)
        self.on_event = on_event
        self.events: List[Dict[str, Any]] = []
        self.pending_error: Optional[RecompileError] = None
        self._fns: Dict[str, Dict[str, Any]] = {}
        # Cumulative wall of cache-miss calls (trace+compile+dispatch;
        # the dispatch of a missing call blocks through compilation).
        # Warmup compiles count too — the goodput ledger attributes ALL
        # compile wall, cold start included.
        self.compile_wall_s = 0.0

    def raise_pending(self) -> None:
        """Raise (once) a fail_on_recompile violation recorded by the last
        call. The raise is DEFERRED out of the instrumented call itself:
        the engine's step functions donate their input state, so raising
        before the caller stores the returned state would strand the
        engine on deleted buffers — the owner pumps this right after the
        state assignment instead."""
        if self.pending_error is not None:
            err, self.pending_error = self.pending_error, None
            raise err

    @property
    def recompile_count(self) -> int:
        """Post-warmup recompiles across every instrumented function."""
        return len(self.events)

    def compile_counts(self) -> Dict[str, int]:
        return {name: st["compiles"] for name, st in self._fns.items()}

    def registered_paths(self) -> Dict[str, Tuple[Callable, Tuple, Dict]]:
        """The registry handoff: {path name: (raw jitted fn, abstract
        args, abstract kwargs)} for every instrumented function that has
        compiled at least once. The abstract signature is the one
        recorded at the LAST compile (ShapeDtypeStructs with shardings —
        they survive buffer donation), so consumers (the roofline cost
        model, the analysis/ lint auditor) can AOT re-lower each path
        host-side with zero device traffic and zero fences."""
        out: Dict[str, Tuple[Callable, Tuple, Dict]] = {}
        for name, st in self._fns.items():
            fn, ab = st.get("fn"), st.get("abstract_args")
            if fn is not None and ab is not None:
                out[name] = (fn, ab[0], ab[1])
        return out

    def instrument(self, name: str, fn: Callable) -> Callable:
        """Wrap ``fn`` (typically a jitted callable). The wrapper preserves
        call/donation semantics; the raw function stays reachable via
        ``__wrapped__`` for introspection (flops profiler, hlo audit)."""
        st = self._fns.setdefault(
            name, {"calls": 0, "compiles": 0, "seen": set(), "descs": None,
                   "compile_wall_s": 0.0, "fn": fn, "abstract_args": None})
        st["fn"] = fn
        cache_size = getattr(fn, "_cache_size", None)
        if not callable(cache_size):
            cache_size = None

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            # Hot-path cost discipline: with _cache_size available, miss
            # detection is two int reads — the O(num-leaves) signature walk
            # runs ONLY on a miss (args are still in scope then). The
            # reported delta is therefore vs the previously COMPILED
            # signature, which is the question the operator is asking.
            # Only the fallback path (no _cache_size) pays the per-call
            # signature, because membership IS its miss detector.
            t_call0 = time.perf_counter()
            if cache_size is not None:
                before = cache_size()
                out = fn(*args, **kwargs)
                miss = cache_size() > before
                descs = abstract_signature((args, kwargs))[1] if miss \
                    else None
            else:
                key, descs = abstract_signature((args, kwargs))
                out = fn(*args, **kwargs)
                miss = key not in st["seen"]
                st["seen"].add(key)
            prior_calls = st["calls"]
            st["calls"] += 1
            if miss:
                # Miss-only work: the call just paid seconds of compile,
                # so clocking it and mirroring the abstract signature
                # (ShapeDtypeStructs survive buffer donation — the cost
                # model AOT-relowers from them at report boundaries) is
                # noise on top.
                dt = time.perf_counter() - t_call0
                st["compile_wall_s"] += dt
                self.compile_wall_s += dt
                from .cost_model import abstract_args_of
                st["abstract_args"] = abstract_args_of(args, kwargs)
                prev_descs, st["descs"] = st["descs"], descs
                st["compiles"] += 1
                if prior_calls >= self.warmup_calls:
                    self._violation(name, st, prev_descs, descs)
            return out

        wrapped.__wrapped__ = fn
        return wrapped

    def _violation(self, name: str, st: Dict[str, Any], prev_descs,
                   descs) -> None:
        delta = signature_delta(prev_descs or (), descs)
        event = {
            "fn": name,
            "call_index": st["calls"] - 1,
            "total_compiles": st["compiles"],
            "signature_delta": delta,
        }
        self.events.append(event)
        if self.on_event is not None:
            self.on_event(dict(event))
        if self.fail_on_recompile:
            self.pending_error = RecompileError(
                f"telemetry.fail_on_recompile: jit cache miss on '{name}' "
                f"after warmup (compile #{st['compiles']} at call "
                f"{st['calls'] - 1}); signature delta: "
                + "; ".join(delta))
