"""Request-scoped distributed tracing for the serving tier.

A ``RequestTrace`` is the host-side record of one request's life:
born at enqueue, carried through the route decision (the chosen
replica plus every candidate's occupancy / queue-depth /
prefix-affinity score), admission attempts and reservation rejections,
prefill (chunk count, prefix-cache hits, CoW forks), every
decode/verify iteration it participates in (batch occupancy and spec
acceptance at that tick), and completion or abort.

Contract (the same one the telemetry spine keeps): **zero added device
syncs**.  Every input here is host-authoritative scheduler/router
state — queue lengths, slot maps, ``perf_counter`` stamps — plus token
counts the engine already fetched in its ONE per-iteration device_get.
This module never imports jax; ``tools/serve_slo_check.py`` fence-
asserts the enabled-vs-disabled ``device_sync_count`` delta is zero.

Storage is ring-buffered: per-request tick marks cap at
``tick_capacity`` (drops counted, never silently), completed timelines
retain the last ``capacity`` records.  On completion a request's
timeline drains into the existing writers:

- one ``request_trace`` JSONL event (the same immediate-write class as
  ``request_complete``), carrying the full span timeline — so
  ``tools/telemetry_report.py`` can reconstruct worst-request
  exemplars from the JSONL alone;
- Perfetto spans on a per-replica lane plus flow arrows
  (``TraceWriter.flow``) linking route→admit→first-token across
  replica tracks.

Timelines are contiguous by construction: consecutive phases share
their boundary instant (queued ends exactly where prefill starts,
prefill ends exactly at first token), so ``validate_timeline`` checks
gaps/overlaps at host-clock resolution exactly, not within an epsilon.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

# Perfetto lanes: training spans own 0-7 (trace._LANES); the serving
# request view gets the router on lane 8 and replicas on 9+.
ROUTER_LANE = 8
_REPLICA_LANE0 = 9


class _Rec:
    """Mutable per-request record while the request is in flight."""

    __slots__ = ("rid", "replica", "t_enqueue", "t_route", "route",
                 "admission_attempts", "t_first_reject", "reject_reason",
                 "t_admit", "slot", "prefill", "t_first", "ticks",
                 "ticks_dropped", "emitted", "t_end", "outcome", "cow_forks")

    def __init__(self, rid: int, t_enqueue: float):
        self.rid = rid
        self.replica: Optional[str] = None
        self.t_enqueue = t_enqueue
        self.t_route: Optional[float] = None
        self.route: Optional[dict] = None
        self.admission_attempts = 0
        self.t_first_reject: Optional[float] = None
        self.reject_reason: Optional[str] = None
        self.t_admit: Optional[float] = None
        self.slot: Optional[int] = None
        self.prefill: Optional[dict] = None
        self.t_first: Optional[float] = None
        self.ticks: List[dict] = []
        self.ticks_dropped = 0
        self.emitted = 0
        self.t_end: Optional[float] = None
        self.outcome: Optional[str] = None
        self.cow_forks = 0


class RequestTrace:
    """Host-side per-request span recorder for a scheduler or router."""

    def __init__(self, capacity: int = 1024, tick_capacity: int = 512,
                 clock=time.perf_counter):
        self.capacity = int(capacity)
        self.tick_capacity = int(tick_capacity)
        self._clock = clock
        self._live: Dict[int, _Rec] = {}
        self.completed: List[dict] = []  # ring of finished timelines
        self.records_dropped = 0
        self._replica_lanes: Dict[str, int] = {}

    # ------------------------------------------------------------- marks
    def enqueue(self, rid: int, t: Optional[float] = None) -> None:
        if rid in self._live:
            return
        if len(self._live) >= self.capacity:
            self.records_dropped += 1
            return
        self._live[rid] = _Rec(rid, self._clock() if t is None else t)

    def route(self, rid: int, chosen: int, candidates: List[dict],
              t: Optional[float] = None) -> None:
        """Record the routing decision with every candidate's scores."""
        rec = self._live.get(rid)
        if rec is None:
            return
        rec.t_route = self._clock() if t is None else t
        rec.route = {"chosen": int(chosen), "candidates": candidates}

    def admit_reject(self, rid: int, reason: str = "reservation",
                     t: Optional[float] = None) -> bool:
        """A failed admission attempt; returns True on the FIRST one."""
        rec = self._live.get(rid)
        if rec is None:
            return False
        rec.admission_attempts += 1
        first = rec.t_first_reject is None
        if first:
            rec.t_first_reject = self._clock() if t is None else t
            rec.reject_reason = reason
        return first

    def admit(self, rid: int, slot: int, t: Optional[float] = None,
              replica: Optional[str] = None) -> None:
        rec = self._live.get(rid)
        if rec is None:
            return
        rec.t_admit = self._clock() if t is None else t
        rec.slot = int(slot)
        if replica is not None:
            rec.replica = replica

    def prefill(self, rid: int, wall_s: float, tokens: int, chunks: int = 1,
                cached_tokens: int = 0, cow_fork: bool = False) -> None:
        rec = self._live.get(rid)
        if rec is None:
            return
        rec.prefill = {"wall_ms": wall_s * 1e3, "tokens": int(tokens),
                       "chunks": int(chunks),
                       "cached_tokens": int(cached_tokens)}
        if cow_fork:
            rec.cow_forks += 1

    def first_token(self, rid: int, t: Optional[float] = None) -> None:
        rec = self._live.get(rid)
        if rec is not None and rec.t_first is None:
            rec.t_first = self._clock() if t is None else t

    def tick(self, rid: int, occupancy: int, emitted: int,
             proposed: int = 0, accepted: int = 0,
             t: Optional[float] = None) -> None:
        """One decode/verify iteration this request participated in."""
        rec = self._live.get(rid)
        if rec is None:
            return
        rec.emitted += int(emitted)
        if len(rec.ticks) >= self.tick_capacity:
            rec.ticks_dropped += 1
            return
        mark = {"t": self._clock() if t is None else t,
                "occupancy": int(occupancy), "emitted": int(emitted)}
        if proposed:
            mark["proposed"] = int(proposed)
            mark["accepted"] = int(accepted)
        rec.ticks.append(mark)

    # ---------------------------------------------------------- lifecycle
    def complete(self, rid: int, t: Optional[float] = None,
                 telemetry=None) -> Optional[dict]:
        return self._finish(rid, "complete", t, telemetry)

    def abort(self, rid: int, reason: str = "abort",
              t: Optional[float] = None, telemetry=None) -> Optional[dict]:
        return self._finish(rid, reason, t, telemetry)

    def _finish(self, rid: int, outcome: str, t: Optional[float],
                telemetry) -> Optional[dict]:
        rec = self._live.pop(rid, None)
        if rec is None:
            return None
        rec.t_end = self._clock() if t is None else t
        rec.outcome = "complete" if outcome == "complete" else "abort"
        tl = self._timeline(rec, outcome)
        self.completed.append(tl)
        if len(self.completed) > self.capacity:
            del self.completed[:len(self.completed) - self.capacity]
        if telemetry is not None:
            self._drain(rec, tl, telemetry)
        return tl

    # ---------------------------------------------------------- timeline
    def _timeline(self, rec: _Rec, outcome: str) -> dict:
        """Build the contiguous span timeline (offsets in ms from enqueue).

        Consecutive spans share boundary instants, so the no-gap/
        no-overlap property holds exactly at host-clock resolution.
        """
        t0 = rec.t_enqueue

        def ms(t: Optional[float]) -> Optional[float]:
            return None if t is None else (t - t0) * 1e3

        spans: List[dict] = []
        # queued: enqueue → admit (or end, if never admitted). The route
        # decision is an instant inside it.
        q_end = rec.t_admit if rec.t_admit is not None else rec.t_end
        spans.append({"phase": "queued", "t_ms": 0.0,
                      "dur_ms": ms(q_end) or 0.0})
        if rec.t_admit is not None:
            # prefill runs to first token, or to the end for a request
            # aborted mid-service — either way no gap before decode/end.
            pf_end = rec.t_first if rec.t_first is not None else rec.t_end
            pf = {"phase": "prefill", "t_ms": ms(rec.t_admit),
                  "dur_ms": (pf_end - rec.t_admit) * 1e3}
            if rec.prefill:
                pf.update(rec.prefill)
            if rec.cow_forks:
                pf["cow_forks"] = rec.cow_forks
            spans.append(pf)
            if rec.t_first is not None:
                spans.append({"phase": "decode", "t_ms": ms(rec.t_first),
                              "dur_ms": (rec.t_end - rec.t_first) * 1e3,
                              "ticks": len(rec.ticks) + rec.ticks_dropped,
                              "emitted": rec.emitted})
        tl: dict = {"rid": rec.rid, "outcome": rec.outcome,
                    "t0_s": rec.t_enqueue, "spans": spans,
                    "total_ms": ms(rec.t_end),
                    "admission_attempts": rec.admission_attempts,
                    "new_tokens": rec.emitted}
        if outcome not in ("complete", "abort"):
            tl["abort_reason"] = outcome
        if rec.replica is not None:
            tl["replica"] = rec.replica
        if rec.route is not None:
            tl["route"] = rec.route
            tl["route_ms"] = ms(rec.t_route)
        if rec.t_first_reject is not None:
            tl["first_reject_ms"] = ms(rec.t_first_reject)
            tl["reject_reason"] = rec.reject_reason
        if rec.t_admit is not None:
            tl["queue_wait_ms"] = ms(rec.t_admit)
        if rec.t_first is not None:
            tl["ttft_ms"] = ms(rec.t_first)
            if rec.t_admit is not None:
                tl["service_ttft_ms"] = (rec.t_first - rec.t_admit) * 1e3
        if rec.ticks:
            tl["ticks"] = [
                {"t_ms": (m["t"] - t0) * 1e3, **{k: v for k, v in m.items()
                                                 if k != "t"}}
                for m in rec.ticks]
        if rec.ticks_dropped:
            tl["ticks_dropped"] = rec.ticks_dropped
        return tl

    # ------------------------------------------------------------- drain
    def _lane(self, replica: Optional[str]) -> int:
        if not replica:
            return _REPLICA_LANE0
        if replica not in self._replica_lanes:
            self._replica_lanes[replica] = \
                _REPLICA_LANE0 + len(self._replica_lanes)
        return self._replica_lanes[replica]

    def _drain(self, rec: _Rec, tl: dict, telemetry) -> None:
        """Emit the finished timeline: one JSONL event + Perfetto spans
        with flow arrows route→admit→first-token. Host file IO only."""
        try:
            telemetry.event("request_trace", tl)
        except Exception:
            pass
        tracer = getattr(telemetry, "tracer", None)
        if tracer is None:
            return
        lane = self._lane(rec.replica)
        t0 = rec.t_enqueue
        prefix = f"req{rec.rid}"
        for sp in tl["spans"]:
            t_abs = t0 + sp["t_ms"] / 1e3
            args = {k: v for k, v in sp.items()
                    if k not in ("phase", "t_ms", "dur_ms")}
            args["rid"] = rec.rid
            tracer.add_span(f"{prefix}/{sp['phase']}", t_abs,
                            sp["dur_ms"] / 1e3,
                            tid=ROUTER_LANE if sp["phase"] == "queued"
                            else lane, args=args)
        # Flow chain: route (router lane) → admit → first token (replica
        # lane) — one arrow per request across tracks.
        t_route = rec.t_route if rec.t_route is not None else rec.t_enqueue
        tracer.flow(prefix, rec.rid, "s", t_route, tid=ROUTER_LANE)
        if rec.t_admit is not None:
            tracer.flow(prefix, rec.rid, "t", rec.t_admit, tid=lane)
        if rec.t_first is not None:
            tracer.flow(prefix, rec.rid, "f", rec.t_first, tid=lane)

    # ------------------------------------------------------------ report
    def summary(self) -> dict:
        return {"completed": len(self.completed),
                "in_flight": len(self._live),
                "records_dropped": self.records_dropped,
                "ticks_dropped": sum(tl.get("ticks_dropped", 0)
                                     for tl in self.completed)}


def validate_timeline(tl: dict) -> List[str]:
    """Check one drained timeline for structural defects.

    Returns a list of problems (empty = valid): spans must be present,
    start at offset 0, be contiguous (each span ends exactly where the
    next begins — shared instants, so equality is exact), and a
    completed request must carry the enqueue→admit→first-token→complete
    chain (queued/prefill/decode with ttft and queue_wait split).
    """
    problems: List[str] = []
    spans = tl.get("spans") or []
    if not spans:
        return ["no spans"]
    if spans[0]["t_ms"] != 0.0:
        problems.append(f"first span starts at {spans[0]['t_ms']}, not 0")
    for a, b in zip(spans, spans[1:]):
        end = a["t_ms"] + a["dur_ms"]
        if end != b["t_ms"]:
            kind = "gap" if end < b["t_ms"] else "overlap"
            problems.append(
                f"{kind} between {a['phase']} and {b['phase']}: "
                f"{end} != {b['t_ms']}")
    last = spans[-1]
    total = tl.get("total_ms")
    if total is not None and last["t_ms"] + last["dur_ms"] != total:
        problems.append("last span does not end at total_ms")
    if tl.get("outcome") == "complete":
        phases = [s["phase"] for s in spans]
        if phases != ["queued", "prefill", "decode"]:
            problems.append(f"completed request has phases {phases}")
        for key in ("ttft_ms", "queue_wait_ms", "service_ttft_ms"):
            if tl.get(key) is None:
                problems.append(f"completed request missing {key}")
        if tl.get("ttft_ms") is not None \
                and tl.get("queue_wait_ms") is not None \
                and tl.get("service_ttft_ms") is not None:
            if abs(tl["queue_wait_ms"] + tl["service_ttft_ms"]
                   - tl["ttft_ms"]) > 1e-6:
                problems.append("queue_wait + service_ttft != ttft")
    return problems
