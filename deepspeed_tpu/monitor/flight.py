"""Crash flight recorder: the forensic state a dying run leaves behind.

A SIGTERM (preemption), an uncaught fault, or a plain crash used to take
the in-flight telemetry ring, the open goodput window, and every anomaly
event down with the process — exactly the evidence a postmortem needs.
The flight recorder keeps a HOST-SIDE mirror of the last N drained step
records, the recent events, and callbacks into the live ring/ledger, and
persists all of it ATOMICALLY (tmp file + ``os.replace``) to
``FLIGHT.json`` on:

- SIGTERM / SIGINT — the handler snapshots the signal-time state (the
  unsettled goodput window and the ring's undrained step ids — pure
  host memory, safe even when the device is hung), then attempts a
  clean ``Telemetry.close()`` (which drains the ring so the last
  records make it into ``last_steps``), then persists and CHAINS to the
  previous handler (default disposition re-raises, so exit codes stay
  honest).
- atexit — a process that exits without ``close()`` still persists.
- explicit ``Telemetry.close()`` — every cleanly-closed run leaves a
  ``reason: "close"`` artifact; the REASON is sticky, so a SIGTERM'd
  run's file says SIGTERM even though close() persisted last.
- hard faults — ``faulthandler.enable()`` onto a sidecar log
  (``flight_fault.log``) when no earlier enable exists, so SIGSEGV
  leaves thread stacks next to the JSON.

``tools/telemetry_report.py`` reports flight-recorder presence and the
recorded reason in its ``health`` section.
"""
from __future__ import annotations

import atexit
import faulthandler
import json
import os
import signal
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger

_SIGNALS = ("SIGTERM", "SIGINT")


def dispatch_prev_handler(prev, signum, frame, own_handler) -> None:
    """Continue a chained signal after our handler ran: call a callable
    prior handler, or re-raise under the default disposition so the
    process reports the true termination signal (None / C-installed
    handlers are opaque — dying by the signal is the only honest
    continuation; SIG_IGN stays ignored). Shared by the flight recorder
    and the checkpoint PreemptSaver (runtime/async_ckpt.py) so every
    member of a handler chain re-raises identically."""
    if callable(prev) and prev is not own_handler:
        prev(signum, frame)
    elif prev in (signal.SIG_DFL, None):
        # If the process disposition still points at the caller (a chain
        # restored it), force the default first — otherwise the re-raise
        # would re-enter it forever.
        try:
            if signal.getsignal(signum) == own_handler:
                signal.signal(signum, signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        try:
            os.kill(os.getpid(), signum)
        except Exception:
            sys.exit(128 + int(signum))


class FlightRecorder:
    """Host-side black box for one Telemetry instance."""

    def __init__(self, path: str, window: int = 64,
                 snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 max_events: int = 128):
        self.path = path
        self.window = int(window)
        self.snapshot_fn = snapshot_fn
        self.last_steps: deque = deque(maxlen=self.window)
        self.last_report: Optional[Dict[str, Any]] = None
        self.events: deque = deque(maxlen=int(max_events))
        self.reason: Optional[str] = None
        self.persist_count = 0
        self.closed_clean = False
        # Live-state callbacks the owner (Telemetry) wires up.
        self.ledger_peek: Optional[Callable[[], Dict[str, Any]]] = None
        self.ledger_summary: Optional[Callable[[], Dict[str, Any]]] = None
        self.ring_steps: Optional[Callable[[], List[int]]] = None
        self.health_summary: Optional[Callable[[], Dict[str, Any]]] = None
        self.watchdog_fires: Optional[Callable[[], int]] = None
        self._at_signal: Optional[Dict[str, Any]] = None
        self._close_cb: Optional[Callable[[], None]] = None
        self._prev_handlers: Dict[int, Any] = {}
        # Kept across uninstall: a NEWER recorder may have chained our
        # handler before we uninstalled, so a stale invocation must
        # still be able to pass the signal through (without touching
        # the artifact).
        self._chain_prev: Dict[int, Any] = {}
        self._installed = False
        self._fault_file = None
        self._atexit_hook = None

    # ------------------------------------------------------------------ #
    # Feed (called by Telemetry at drain time / on events)
    # ------------------------------------------------------------------ #
    def note_step(self, rec: Dict[str, Any]) -> None:
        self.last_steps.append(rec)

    def note_report(self, rec: Dict[str, Any]) -> None:
        self.last_report = rec

    def note_event(self, rec: Dict[str, Any]) -> None:
        self.events.append(rec)

    # ------------------------------------------------------------------ #
    # Install / uninstall
    # ------------------------------------------------------------------ #
    def install(self, close_cb: Optional[Callable[[], None]] = None) -> None:
        """Hook SIGTERM/SIGINT (chaining any previous handler), atexit,
        and — when nothing else enabled it — faulthandler onto a sidecar
        log next to FLIGHT.json."""
        if self._installed:
            return
        self._installed = True
        self._close_cb = close_cb
        for name in _SIGNALS:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                prev = signal.signal(signum, self._on_signal)
                self._prev_handlers[int(signum)] = prev
            except (ValueError, OSError):
                # Not the main thread / restricted env: signals are a
                # best-effort layer; atexit + explicit close still work.
                pass
        self._atexit_hook = self._on_atexit
        atexit.register(self._atexit_hook)
        if not faulthandler.is_enabled():
            try:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fault_file = open(
                    os.path.join(d or ".", "flight_fault.log"), "w")
                faulthandler.enable(file=self._fault_file)
            except Exception:
                self._fault_file = None

    def uninstall(self) -> None:
        """Restore chained handlers and drop the atexit hook (idempotent
        — the signal handler itself calls this mid-flight)."""
        if not self._installed:
            return
        self._installed = False
        self._chain_prev.update(self._prev_handlers)
        for signum, prev in self._prev_handlers.items():
            try:
                if signal.getsignal(signum) == self._on_signal:
                    # A None prior handler (installed from C) cannot be
                    # re-installed from Python; default disposition is
                    # the closest restoration (and prevents our handler
                    # from re-entering itself on the re-raise).
                    signal.signal(signum, signal.SIG_DFL
                                  if prev is None else prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_handlers = {}
        if self._atexit_hook is not None:
            atexit.unregister(self._atexit_hook)
            self._atexit_hook = None
        if self._fault_file is not None:
            try:
                faulthandler.disable()
                self._fault_file.close()
            except Exception:
                pass
            self._fault_file = None

    # ------------------------------------------------------------------ #
    # Triggers
    # ------------------------------------------------------------------ #
    def _on_signal(self, signum, frame) -> None:
        if not self._installed:
            # Stale link in a handler chain: a newer recorder (same
            # process, e.g. a second engine) chained this handler before
            # our uninstall. The live recorder already persisted ITS
            # artifact — touching ours now would clobber the postmortem
            # with a dead engine's state. Pass the signal through.
            self._dispatch_prev(self._chain_prev.get(int(signum),
                                                     signal.SIG_DFL),
                                signum, frame)
            return
        try:
            name = signal.Signals(signum).name
        except Exception:
            name = f"signal {signum}"
        self.note_signal(name)
        # Persist the host-safe snapshot FIRST: the clean close below
        # drains the ring with a device_get, and on a HUNG device (the
        # flagship hang-then-SIGTERM scenario) that blocks until the
        # grace period's SIGKILL — the artifact must already be on disk
        # by then. A successful close upgrades it with a second persist.
        self.persist()
        prev = self._prev_handlers.get(int(signum), signal.SIG_DFL)
        try:
            if self._close_cb is not None:
                self._close_cb()   # drains the ring -> last_steps fills
        except Exception:
            pass
        self.persist()
        self.uninstall()
        self._dispatch_prev(prev, signum, frame)

    def _dispatch_prev(self, prev, signum, frame) -> None:
        dispatch_prev_handler(prev, signum, frame, self._on_signal)

    def note_signal(self, name: str) -> None:
        """Snapshot the signal-time state BEFORE any drain runs: the
        unsettled goodput window and the undrained ring step ids are
        pure host memory — capturing them cannot block on a hung
        device."""
        if self.reason is None:
            self.reason = name
        snap: Dict[str, Any] = {"ts": time.time()}
        try:
            if self.ledger_peek is not None:
                snap["goodput_unsettled"] = self.ledger_peek()
            if self.ring_steps is not None:
                snap["undrained_steps"] = list(self.ring_steps())
        except Exception:
            pass
        if self._at_signal is None:
            self._at_signal = snap

    def _on_atexit(self) -> None:
        if self.reason is None:
            self.reason = "atexit"
        try:
            self.persist()
        except Exception:
            pass

    # ------------------------------------------------------------------ #
    # The write
    # ------------------------------------------------------------------ #
    def persist(self, reason: Optional[str] = None) -> Optional[str]:
        """Atomically write FLIGHT.json. The first recorded reason is
        sticky: a SIGTERM'd run's artifact says SIGTERM even though the
        chained close() persists again afterwards."""
        if self.reason is None and reason is not None:
            self.reason = reason
        payload: Dict[str, Any] = {
            "flight_recorder": 1,
            "reason": self.reason or "unknown",
            "ts": time.time(),
            "pid": os.getpid(),
            "persist_count": self.persist_count + 1,
            "closed_clean": self.closed_clean,
            "last_steps": list(self.last_steps),
            "last_report": self.last_report,
            "events": list(self.events),
        }
        if self.snapshot_fn is not None:
            try:
                payload["snapshot"] = self.snapshot_fn()
            except Exception:
                payload["snapshot"] = None
        at_sig = self._at_signal
        try:
            if at_sig is not None:
                # The signal-time view: what was open when the run died.
                payload["at_signal"] = at_sig
                payload["goodput_unsettled"] = \
                    at_sig.get("goodput_unsettled")
                payload["undrained_steps"] = \
                    at_sig.get("undrained_steps", [])
            else:
                if self.ledger_peek is not None:
                    payload["goodput_unsettled"] = self.ledger_peek()
                if self.ring_steps is not None:
                    payload["undrained_steps"] = list(self.ring_steps())
            if self.ledger_summary is not None:
                payload["goodput_totals"] = self.ledger_summary()
            if self.health_summary is not None:
                payload["anomalies"] = self.health_summary()
            if self.watchdog_fires is not None:
                payload["watchdog_fires"] = int(self.watchdog_fires())
        except Exception:
            pass
        if payload["last_steps"]:
            payload["final_step"] = payload["last_steps"][-1].get("step")
        tmp = self.path + ".tmp"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, default=str)
            os.replace(tmp, self.path)
            self.persist_count += 1
            return self.path
        except OSError as e:
            # A deleted tmp dir at interpreter teardown must not turn a
            # crash handler into a second crash.
            try:
                logger.debug(f"flight recorder persist failed: {e}")
            except Exception:
                pass
            return None


__all__ = ["FlightRecorder", "dispatch_prev_handler"]
