"""Telemetry core: per-step records in a ring buffer, drained to JSONL at
report boundaries — with ZERO added host<->device syncs on the hot path.

The hot-path contract (the engine's ``_maybe_log`` discipline, extended):

- ``record_step`` appends the step's metrics dict AS-IS to a bounded ring
  buffer. jax scalars are async futures — holding them costs a few bytes
  of device memory and forces nothing.
- ``maybe_drain`` fires only at report boundaries (``report_steps``,
  default = ``steps_per_print``): ONE batched ``jax.device_get`` over
  every buffered scalar, then JSONL writes, the memory-watermark sample,
  and the trace flush. Between boundaries the subsystem performs no
  device access of any kind.
- When the ring overflows before a drain, the OLDEST records drop and the
  drain's report record says how many (no silent truncation).

The JSONL stream is line records tagged by ``kind``:

- ``meta``   — once per run: dp, zero stage, precision, grad-sync mode,
  analytic wire bytes/step, analytic per-device model-state bytes.
- ``step``   — one per train step (loss, lr, loss_scale, overflow,
  grad_norm, wall_ms, wire_bytes, ``mfu`` once the cost model is armed,
  offload phase timings + overlap fraction when offloading).
- ``report`` — one per drain: samples/sec window, ``window_mfu``,
  skipped steps, device memory sample, the goodput ledger's settled
  window, dropped-record count.
- ``event``  — recompile sentinel hits, memory watermarks, user events.
- ``cost_model`` — once per run (first report boundary): per-path
  roofline verdicts from XLA cost analysis + the jaxpr-walk flops
  profiler + the wire model (see monitor/cost_model.py).

``tools/telemetry_report.py`` summarizes a stream into TELEMETRY.json.
"""
from __future__ import annotations

import atexit
import json
import os
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .cost_model import mfu as _mfu_formula
from .goodput import GoodputLedger, extract_step_info
from .memory import MemoryWatermark, analytic_state_bytes, device_memory_stats
from .peaks import ChipPeaks
from .recompile import RecompileSentinel
from .trace import ProfilerWindow, TraceWriter
from ..utils.logging import log_dist, logger


def _to_py(v: Any) -> Any:
    """Host-native scalar for JSON (called at drain time, post-sync)."""
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    if isinstance(v, np.ndarray) and v.ndim == 0:
        return _to_py(v[()])
    if hasattr(v, "dtype") and getattr(v, "ndim", 1) == 0:
        return _to_py(np.asarray(v)[()])
    return v


class JsonlSink:
    """Line-JSON event sink with the resource story the old engine
    ``_Monitor`` lacked: the file opens on PROCESS 0 ONLY (every SPMD
    process used to append to the same file), ``close()`` is idempotent,
    and an atexit hook closes stragglers. Tensorboard scalars ride along
    when the writer is importable."""

    def __init__(self, output_path: str, job_name: str,
                 tensorboard: bool = False, is_writer: Optional[bool] = None):
        if is_writer is None:
            try:
                import jax
                is_writer = jax.process_index() == 0
            except Exception:
                is_writer = True
        self.is_writer = bool(is_writer)
        self.closed = False
        self.jsonl = None
        self.writer = None
        out = output_path or "./runs"
        self.path = os.path.join(out, f"{job_name}.jsonl")
        if not self.is_writer:
            return
        os.makedirs(out, exist_ok=True)
        self.jsonl = open(self.path, "a")
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self.writer = SummaryWriter(log_dir=os.path.join(out, job_name))
            except Exception:
                self.writer = None
        atexit.register(self.close)

    def write(self, rec: Dict[str, Any]) -> None:
        if self.closed or self.jsonl is None:
            return
        self.jsonl.write(json.dumps(rec) + "\n")
        self.jsonl.flush()
        if self.writer is not None and rec.get("kind") == "step":
            step = int(rec.get("step", 0))
            for k, v in rec.items():
                if k not in ("kind", "step", "ts") and \
                        isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    self.writer.add_scalar(f"Train/{k}", v, step)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        atexit.unregister(self.close)
        if self.jsonl is not None:
            self.jsonl.close()
            self.jsonl = None
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
            self.writer = None


class Telemetry:
    """The engine-facing facade over the monitor subsystem. Disabled
    (default) it is inert: every hot-path method is a single attribute
    test, no files open, no wrapping happens."""

    def __init__(self, cfg, default_report_steps: int = 10,
                 meta: Optional[Dict[str, Any]] = None,
                 is_writer: Optional[bool] = None):
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enabled", False))
        self.meta: Dict[str, Any] = dict(meta or {})
        self.step_provider: Callable[[], int] = lambda: -1
        self.sentinel: Optional[RecompileSentinel] = None
        self.tracer: Optional[TraceWriter] = None
        self.watermark: Optional[MemoryWatermark] = None
        self.sink: Optional[JsonlSink] = None
        self.profiler: Optional[ProfilerWindow] = None
        self.ledger: Optional[GoodputLedger] = None
        self.cost_model_payload: Optional[Dict[str, Any]] = None
        self._mfu_arm: Optional[Dict[str, Any]] = None
        self._compile_wall_seen = 0.0
        self._ckpt_depth = 0
        self.dropped_records = 0
        self.events: List[Dict[str, Any]] = []
        self._closed = False
        if not self.enabled:
            return
        # Goodput ledger: the first window opens NOW (engine init time
        # lands in its "other" bucket — honest, not hidden).
        self.ledger = GoodputLedger()
        self.report_steps = int(cfg.report_steps) or \
            max(1, int(default_report_steps))
        self._ring: deque = deque(maxlen=int(cfg.buffer_size))
        self.sink = JsonlSink(cfg.output_path, cfg.job_name,
                              tensorboard=getattr(cfg, "tensorboard", False),
                              is_writer=is_writer)
        if cfg.trace_path:
            self.tracer = TraceWriter(cfg.trace_path, is_writer=is_writer)
        # Non-writer SPMD processes keep the sentinel/watermark checks but
        # skip step-record collection entirely: buffering scalars and
        # batch-fetching them at drains only to feed a null sink would be
        # pinned memory and a pointless device round trip per boundary.
        self._collect = self.sink.is_writer or self.tracer is not None
        self.sentinel = RecompileSentinel(
            warmup_calls=cfg.recompile_warmup_calls,
            fail_on_recompile=cfg.fail_on_recompile,
            on_event=self._on_recompile)
        if int(cfg.profile_start_step) >= 0:
            out = cfg.profile_dir or os.path.join(
                cfg.output_path or "./runs", "jax_trace")
            self.profiler = ProfilerWindow(cfg.profile_start_step,
                                           cfg.profile_num_steps, out)
        self._meta_written = False
        atexit.register(self.close)

    # ------------------------------------------------------------------ #
    # Hot path (per step): append-only, no device access
    # ------------------------------------------------------------------ #
    def record_step(self, step: int, metrics: Dict[str, Any],
                    **host_fields: Any) -> None:
        """Buffer one step's record. ``metrics`` values may be (and on the
        jitted paths are) un-fetched jax scalars; they sync only at the
        next drain."""
        if not self.enabled or not self._collect:
            return
        if len(self._ring) == self._ring.maxlen:
            self.dropped_records += 1
        self._ring.append((int(step), time.time(), dict(metrics),
                           host_fields))

    def profiler_tick(self, step: int) -> None:
        if self.profiler is not None:
            self.profiler.tick(step)

    def span(self, name: str, **args):
        """Host-span context manager. Feeds the trace writer (when a
        trace_path is set) and, for ``checkpoint_*`` spans, the goodput
        ledger's checkpoint bucket — outermost span only, so the
        pipeline engine's nested per-layer spans don't double-count."""
        bucket = "checkpoint" if name.startswith("checkpoint_") else None
        if self.tracer is None and (bucket is None or self.ledger is None):
            return nullcontext()
        return self._span_ctx(name, bucket, args)

    @contextmanager
    def _span_ctx(self, name: str, bucket: Optional[str],
                  args: Dict[str, Any]):
        outermost = False
        if bucket is not None and self.ledger is not None:
            outermost = self._ckpt_depth == 0
            self._ckpt_depth += 1
        t0 = time.perf_counter()
        try:
            if self.tracer is not None:
                with self.tracer.span(name, **args):
                    yield
            else:
                yield
        finally:
            if bucket is not None and self.ledger is not None:
                self._ckpt_depth -= 1
                if outermost:
                    self.ledger.note(bucket, time.perf_counter() - t0)

    def add_span(self, name: str, t_start: float, dur_s: float,
                 args: Optional[Dict[str, Any]] = None) -> None:
        if self.tracer is not None:
            self.tracer.add_span(name, t_start, dur_s, args=args)

    def instrument_step_fn(self, name: str, fn: Callable) -> Callable:
        """Recompile-sentinel wrapping for a compiled step function;
        identity when telemetry is disabled."""
        if self.sentinel is None:
            return fn
        return self.sentinel.instrument(name, fn)

    def raise_pending(self) -> None:
        """Surface a deferred fail_on_recompile violation (see
        RecompileSentinel.raise_pending — the raise must happen AFTER the
        caller stored the donated step's returned state)."""
        if self.sentinel is not None:
            self.sentinel.raise_pending()

    # ------------------------------------------------------------------ #
    # Offload trace synthesis: spans from the ALREADY-fenced per-bucket
    # timings run_bucketed_step measured — no new fences.
    # ------------------------------------------------------------------ #
    def add_offload_trace(self, timings: Dict[str, Any]) -> None:
        if self.tracer is None or not timings:
            return
        origin = timings.get("t_origin")
        pb = timings.get("per_bucket")
        t0s = timings.get("per_bucket_t0")
        if origin is None or not pb or not t0s:
            return
        phase_names = {"d2h_ms": "offload_d2h", "norm_ms": "offload_norm",
                       "adam_ms": "offload_adam", "h2d_ms": "offload_h2d"}
        for key, span_name in phase_names.items():
            starts = t0s.get(key.replace("_ms", "_t0"))
            durs = pb.get(key)
            if starts is None or durs is None:
                continue
            for b, (t0, ms) in enumerate(zip(starts, durs)):
                if ms <= 0.0:
                    continue
                self.tracer.add_span(f"{span_name} b{b}", origin + t0,
                                     ms / 1e3,
                                     tid=self.tracer.lane(span_name))

    # ------------------------------------------------------------------ #
    # Events (immediate write — rare, structured)
    # ------------------------------------------------------------------ #
    def event(self, kind: str, payload: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        # Meta must LEAD the stream: telemetry_report treats a meta
        # record as a new-run boundary and resets its accumulators, so
        # an event written before the first drain (an early recompile, a
        # serving request completing inside the first report window)
        # would otherwise be dropped from the summary.
        self._ensure_meta()
        rec = {"kind": "event", "event": kind,
               "step": int(self.step_provider()), "ts": time.time(),
               **payload}
        self.events.append(rec)
        self._write(rec)
        if self.tracer is not None:
            self.tracer.instant(kind, args=payload)

    def _on_recompile(self, event: Dict[str, Any]) -> None:
        log_dist(
            f"telemetry: recompile of '{event['fn']}' after warmup "
            f"(compile #{event['total_compiles']}); signature delta: "
            + "; ".join(event["signature_delta"]), ranks=[0])
        self.event("recompile", event)

    @property
    def recompile_count(self) -> int:
        return self.sentinel.recompile_count if self.sentinel else 0

    # ------------------------------------------------------------------ #
    # Cost model (roofline + MFU) arming — report-boundary work
    # ------------------------------------------------------------------ #
    def set_cost_model(self, payload: Dict[str, Any],
                       samples_per_step: Optional[int] = None) -> None:
        """Record the built cost model (one ``cost_model`` JSONL record)
        and arm per-step MFU: subsequent drains stamp ``mfu`` onto every
        step record from its wall and the armed flops/peak — no extra
        device access (wall is already host data)."""
        if not self.enabled:
            return
        self.cost_model_payload = payload
        self._ensure_meta()
        self._write({"kind": "cost_model", "ts": time.time(), **payload})
        step = payload.get("step") or {}
        chip = payload.get("chip") or {}
        flops = float(step.get("flops_per_step") or 0.0)
        n_dev = int(payload.get("n_devices") or 1)
        try:
            peaks = ChipPeaks(**chip)
        except TypeError:
            return
        if flops > 0 and peaks.bf16_tflops > 0:
            self._mfu_arm = {
                "flops_per_step": flops,
                "peaks": peaks,
                "n_devices": n_dev,
                "samples_per_step": samples_per_step,
            }

    def _step_mfu(self, step_time_s: float) -> Optional[float]:
        """The shared MFU formula (cost_model.mfu) at the armed per-step
        flops/peak — one definition for per-step and window figures."""
        arm = self._mfu_arm
        if arm is None or step_time_s <= 0:
            return None
        return _mfu_formula(arm["flops_per_step"], step_time_s,
                            arm["n_devices"], arm["peaks"])

    # ------------------------------------------------------------------ #
    # Report boundary
    # ------------------------------------------------------------------ #
    def set_analytic_footprint(self, nbytes: int,
                               sampler: Optional[Callable] = None) -> None:
        """Arm the memory watermark with the analytic per-device
        model-state bytes (see monitor/memory.py)."""
        if not self.enabled or not self.cfg.memory_watermarks:
            return
        self.watermark = MemoryWatermark(
            nbytes, ratio=self.cfg.watermark_ratio,
            slack_bytes=self.cfg.watermark_slack_bytes,
            sampler=sampler or device_memory_stats)
        self.meta["analytic_state_bytes"] = int(nbytes)

    def maybe_drain(self, step: int,
                    extra: Optional[Dict[str, Any]] = None,
                    extra_fn: Optional[Callable[[], Dict[str, Any]]] = None
                    ) -> bool:
        """Drain iff ``step`` is a report boundary. ``extra_fn`` is only
        invoked when the drain fires — callers can defer work (e.g. a
        counter sync) that must not run on non-boundary steps."""
        if not self.enabled or step % self.report_steps != 0:
            return False
        if extra is None and extra_fn is not None:
            extra = extra_fn()
        self.drain(extra)
        return True

    def drain(self, extra: Optional[Dict[str, Any]] = None) -> None:
        """Flush the ring to JSONL: one batched device_get for every
        buffered scalar, then the memory sample + watermark check."""
        if not self.enabled:
            return
        self._ensure_meta()
        recs = list(self._ring)
        self._ring.clear()
        # One sync for the whole window.
        import jax
        pending = []
        for _, _, metrics, _ in recs:
            for v in metrics.values():
                if isinstance(v, jax.Array):
                    pending.append(v)
        fetched = iter(jax.device_get(pending)) if pending else iter(())
        step_infos = []
        for step, ts, metrics, host_fields in recs:
            rec: Dict[str, Any] = {"kind": "step", "step": step, "ts": ts}
            for k, v in metrics.items():
                rec[k] = _to_py(next(fetched) if isinstance(v, jax.Array)
                                else v)
            for k, v in host_fields.items():
                rec[k] = _to_py(v) if not isinstance(v, dict) else v
            wall_ms = rec.get("wall_ms")
            if isinstance(wall_ms, (int, float)):
                m = self._step_mfu(float(wall_ms) / 1e3)
                if m is not None:
                    # Per-step MFU from dispatch wall (see the wall_ms
                    # honesty note); the fenced figure is window_mfu.
                    # 4 significant digits, NOT fixed decimals — a tiny
                    # dev-model MFU (1e-10 on a CPU mesh) must stay
                    # nonzero.
                    rec["mfu"] = float(f"{m:.4g}")
            step_infos.append(extract_step_info(rec))
            self._write(rec)
        report: Dict[str, Any] = {
            "kind": "report", "step": int(self.step_provider()),
            "ts": time.time(), "records": len(recs),
            "dropped_records": self.dropped_records,
        }
        self.dropped_records = 0
        if extra:
            report.update({k: _to_py(v) if not isinstance(v, dict) else v
                           for k, v in extra.items()})
        if self._mfu_arm is not None and report.get("samples_per_sec_valid") \
                and report.get("samples_per_sec") \
                and self._mfu_arm.get("samples_per_step"):
            # Fenced window MFU: the throughput timer's synchronized
            # window average, not dispatch wall.
            step_time_s = self._mfu_arm["samples_per_step"] / \
                float(report["samples_per_sec"])
            m = self._step_mfu(step_time_s)
            if m is not None:
                report["window_mfu"] = float(f"{m:.4g}")
        if self.ledger is not None:
            if self.sentinel is not None:
                delta = self.sentinel.compile_wall_s - \
                    self._compile_wall_seen
                self._compile_wall_seen = self.sentinel.compile_wall_s
                self.ledger.note("recompile", delta)
            report["goodput"] = self.ledger.close_window(step_infos)
        if self.watermark is not None:
            stats, wm_event = self.watermark.check()
            report["memory"] = stats if stats is not None \
                else {"available": False}
            if wm_event is not None:
                logger.warning(
                    "telemetry: device memory watermark exceeded — peak "
                    f"{wm_event['peak_bytes_in_use_max'] / 2**30:.2f} GB vs "
                    f"analytic model-state "
                    f"{wm_event['analytic_state_bytes'] / 2**30:.2f} GB "
                    f"(x{wm_event['ratio']}); a sharding regression can "
                    "look exactly like this")
                self.event("memory_watermark", wm_event)
        self._write(report)
        if self.tracer is not None:
            self.tracer.flush()

    def _ensure_meta(self) -> None:
        if self._meta_written:
            return
        self._meta_written = True
        self._write({"kind": "meta", "ts": time.time(), **self.meta})

    def _write(self, rec: Dict[str, Any]) -> None:
        if self.sink is not None:
            self.sink.write(rec)

    # ------------------------------------------------------------------ #
    def close(self) -> None:
        if not self.enabled or self._closed:
            return
        if self._ring or (self.ledger is not None
                          and self.ledger.has_pending()):
            # Drain buffered steps AND settle any trailing attributed
            # time (a checkpoint saved after the last report boundary
            # must not vanish from the goodput ledger).
            self.drain()
        else:
            self._ensure_meta()
        self._closed = True
        # Release process-lifetime anchors: the atexit hook keeps this
        # object (and anything its callbacks close over) alive, so a
        # closed Telemetry must unhook itself and drop the engine-side
        # step_provider closure — otherwise every engine ever built with
        # telemetry enabled pins its full device state until exit.
        atexit.unregister(self.close)
        self.step_provider = lambda: -1
        if self.profiler is not None:
            self.profiler.stop()
        if self.tracer is not None:
            self.tracer.close()
        if self.sink is not None:
            self.sink.close()


__all__ = ["Telemetry", "JsonlSink", "analytic_state_bytes",
           "device_memory_stats"]
