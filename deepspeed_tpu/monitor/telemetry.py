"""Telemetry core: per-step records in a ring buffer, drained to JSONL at
report boundaries — with ZERO added host<->device syncs on the hot path.

The hot-path contract (the engine's ``_maybe_log`` discipline, extended):

- ``record_step`` appends the step's metrics dict AS-IS to a bounded ring
  buffer. jax scalars are async futures — holding them costs a few bytes
  of device memory and forces nothing.
- ``maybe_drain`` fires only at report boundaries (``report_steps``,
  default = ``steps_per_print``): ONE batched ``jax.device_get`` over
  every buffered scalar, then JSONL writes, the memory-watermark sample,
  and the trace flush. Between boundaries the subsystem performs no
  device access of any kind.
- When the ring overflows before a drain, the OLDEST records drop and the
  drain's report record says how many (no silent truncation).

The JSONL stream is line records tagged by ``kind``:

- ``meta``   — once per run: dp, zero stage, precision, grad-sync mode,
  analytic wire bytes/step, analytic per-device model-state bytes.
- ``step``   — one per train step (loss, lr, loss_scale, overflow,
  grad_norm, wall_ms, wire_bytes, ``mfu`` once the cost model is armed,
  offload phase timings + overlap fraction when offloading).
- ``report`` — one per drain: samples/sec window, ``window_mfu``,
  skipped steps, device memory sample, the goodput ledger's settled
  window, dropped-record count.
- ``event``  — recompile sentinel hits, memory watermarks, anomaly and
  watchdog events (monitor/health.py), user events.
- ``cost_model`` — once per run (first report boundary): per-path
  roofline verdicts from XLA cost analysis + the jaxpr-walk flops
  profiler + the wire model (see monitor/cost_model.py).
- ``final``  — the terminal drain marker ``close()`` writes. A run
  segment that ends WITHOUT one was truncated (crash, kill -9, lost
  pod) and ``tools/telemetry_report.py`` says so instead of presenting
  partial-window stats as a complete run.

Multi-host: rank 0 writes the primary stream; with
``telemetry.per_host_shards`` every other process writes
``<job>.rankK.jsonl`` (monitor/hostinfo.py is the one writer resolver)
instead of the historical silent record drop, and the report tool
aggregates the shards (straggler skew, step-count/loss desync).

``tools/telemetry_report.py`` summarizes a stream into TELEMETRY.json.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .cost_model import mfu as _mfu_formula
from .flight import FlightRecorder
from .goodput import GoodputLedger, extract_step_info
from .health import HangWatchdog, HealthMonitor
from .hostinfo import resolve_writer, shard_path
from .memory import MemoryWatermark, analytic_state_bytes, device_memory_stats
from .peaks import ChipPeaks
from .recompile import RecompileSentinel
from .trace import ProfilerWindow, TraceWriter
from ..utils.logging import log_dist, logger

# The metrics key the engines' in-graph health tap rides under; popped
# from the record at drain time (provenance feeds anomaly events, not
# the per-step JSONL, which keeps its scalar-only shape).
HEALTH_TAP_KEY = "health_leaf_sq"


def _to_py(v: Any) -> Any:
    """Host-native scalar for JSON (called at drain time, post-sync)."""
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    if isinstance(v, np.ndarray) and v.ndim == 0:
        return _to_py(v[()])
    if hasattr(v, "dtype") and getattr(v, "ndim", 1) == 0:
        return _to_py(np.asarray(v)[()])
    return v


class JsonlSink:
    """Line-JSON event sink with the resource story the old engine
    ``_Monitor`` lacked: process 0 writes the primary stream (every SPMD
    process used to append to the same file); with ``per_host`` every
    other process writes its own ``<job>.rankK.jsonl`` shard (the
    hostinfo resolver — no more silent record drop on non-writers);
    ``close()`` is idempotent, and an atexit hook closes stragglers.
    Tensorboard scalars ride along when the writer is importable."""

    def __init__(self, output_path: str, job_name: str,
                 tensorboard: bool = False, is_writer: Optional[bool] = None,
                 per_host: bool = False, rank: Optional[int] = None,
                 world: Optional[int] = None):
        self.is_writer, self.rank, self.world = resolve_writer(
            is_writer, per_host=per_host, rank=rank, world=world)
        self.closed = False
        self.jsonl = None
        self.writer = None
        self._lock = threading.Lock()   # watchdog events write off-thread
        out = output_path or "./runs"
        self.path = shard_path(os.path.join(out, f"{job_name}.jsonl"),
                               self.rank if self.is_writer else 0)
        if not self.is_writer:
            if self.world > 1 and not per_host:
                # The drop is a policy now, not an accident: say so once.
                logger.info(
                    f"telemetry: process {self.rank} discards step records "
                    f"(set telemetry.per_host_shards for a per-host JSONL "
                    f"shard)")
            return
        os.makedirs(out, exist_ok=True)
        self.jsonl = open(self.path, "a")
        if tensorboard and self.rank == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self.writer = SummaryWriter(log_dir=os.path.join(out, job_name))
            except Exception:
                self.writer = None
        atexit.register(self.close)

    def write(self, rec: Dict[str, Any]) -> None:
        if self.closed or self.jsonl is None:
            return
        with self._lock:
            self.jsonl.write(json.dumps(rec) + "\n")
            self.jsonl.flush()
        if self.writer is not None and rec.get("kind") == "step":
            step = int(rec.get("step", 0))
            for k, v in rec.items():
                if k not in ("kind", "step", "ts") and \
                        isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    self.writer.add_scalar(f"Train/{k}", v, step)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        atexit.unregister(self.close)
        if self.jsonl is not None:
            self.jsonl.close()
            self.jsonl = None
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
            self.writer = None


class Telemetry:
    """The engine-facing facade over the monitor subsystem. Disabled
    (default) it is inert: every hot-path method is a single attribute
    test, no files open, no wrapping happens."""

    def __init__(self, cfg, default_report_steps: int = 10,
                 meta: Optional[Dict[str, Any]] = None,
                 is_writer: Optional[bool] = None):
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enabled", False))
        self.meta: Dict[str, Any] = dict(meta or {})
        self.step_provider: Callable[[], int] = lambda: -1
        self.sentinel: Optional[RecompileSentinel] = None
        self.tracer: Optional[TraceWriter] = None
        self.watermark: Optional[MemoryWatermark] = None
        self.sink: Optional[JsonlSink] = None
        self.profiler: Optional[ProfilerWindow] = None
        self.ledger: Optional[GoodputLedger] = None
        self.health: Optional[HealthMonitor] = None
        self.watchdog: Optional[HangWatchdog] = None
        self.flight: Optional[FlightRecorder] = None
        self.cost_model_payload: Optional[Dict[str, Any]] = None
        self._mfu_arm: Optional[Dict[str, Any]] = None
        self._compile_wall_seen = 0.0
        self._ckpt_depth = 0
        self.dropped_records = 0
        self.events: List[Dict[str, Any]] = []
        self._closed = False
        if not self.enabled:
            return
        # Goodput ledger: the first window opens NOW (engine init time
        # lands in its "other" bucket — honest, not hidden).
        self.ledger = GoodputLedger()
        self.report_steps = int(cfg.report_steps) or \
            max(1, int(default_report_steps))
        self._ring: deque = deque(maxlen=int(cfg.buffer_size))
        per_host = bool(getattr(cfg, "per_host_shards", False))
        self.sink = JsonlSink(cfg.output_path, cfg.job_name,
                              tensorboard=getattr(cfg, "tensorboard", False),
                              is_writer=is_writer, per_host=per_host)
        self.meta.setdefault("process_index", self.sink.rank)
        self.meta.setdefault("process_count", self.sink.world)
        # close() writes a terminal `final` record; the report tool uses
        # this capability flag to call a marker-less segment truncated.
        self.meta.setdefault("emits_final", True)
        if cfg.trace_path:
            self.tracer = TraceWriter(cfg.trace_path, is_writer=is_writer,
                                      per_host=per_host)
        # Non-writer SPMD processes keep the sentinel/watermark checks but
        # skip step-record collection entirely: buffering scalars and
        # batch-fetching them at drains only to feed a null sink would be
        # pinned memory and a pointless device round trip per boundary.
        self._collect = self.sink.is_writer or self.tracer is not None
        self.sentinel = RecompileSentinel(
            warmup_calls=cfg.recompile_warmup_calls,
            fail_on_recompile=cfg.fail_on_recompile,
            on_event=self._on_recompile)
        # Health layer (monitor/health.py + flight.py): drain-time
        # anomaly detection, the hang watchdog, the crash flight
        # recorder. All host-side — the only in-graph piece is the
        # engines' leaf tap, which rides the ring like any other metric.
        hc = getattr(cfg, "health", None)
        if hc is not None and getattr(hc, "enabled", False):
            self.meta.setdefault("health_enabled", True)
            self.health = HealthMonitor(
                z_threshold=hc.z_threshold, ewma_alpha=hc.ewma_alpha,
                warmup_steps=hc.warmup_steps)
            if hc.watchdog:
                self.watchdog = HangWatchdog(
                    factor=hc.watchdog_factor,
                    min_timeout_s=hc.watchdog_min_s,
                    dump_dir=cfg.output_path or "./runs",
                    on_fire=lambda ev: self.event("watchdog", ev))
                self.watchdog.start()
            if hc.flight_recorder and self.sink.is_writer:
                # An explicit flight_path shards per rank too — with
                # per_host on, every rank persisting to ONE file would
                # let the last handler clobber the primary's postmortem.
                fpath = shard_path(
                    hc.flight_path or os.path.join(
                        cfg.output_path or "./runs", "FLIGHT.json"),
                    self.sink.rank)
                self.flight = FlightRecorder(
                    fpath, window=hc.flight_window,
                    snapshot_fn=self._flight_snapshot)
                fl = self.flight
                fl.ledger_peek = lambda: (self.ledger.peek()
                                          if self.ledger else {})
                fl.ledger_summary = lambda: (self.ledger.summary()
                                             if self.ledger else {})
                fl.ring_steps = lambda: [s for s, _, _, _ in self._ring]
                fl.health_summary = lambda: (self.health.summary()
                                             if self.health else {})
                fl.watchdog_fires = lambda: (self.watchdog.fires
                                             if self.watchdog else 0)
                fl.install(close_cb=self.close)
                self.meta.setdefault("flight_path", fpath)
        self._profile_out = cfg.profile_dir or os.path.join(
            cfg.output_path or "./runs", "jax_trace")
        self._profile_done: List[Dict[str, Any]] = []
        if int(cfg.profile_start_step) >= 0:
            self.profiler = ProfilerWindow(cfg.profile_start_step,
                                           cfg.profile_num_steps,
                                           self._profile_out,
                                           on_event=self._profiler_event)
        self._meta_written = False
        atexit.register(self.close)

    # ------------------------------------------------------------------ #
    # Hot path (per step): append-only, no device access
    # ------------------------------------------------------------------ #
    def record_step(self, step: int, metrics: Dict[str, Any],
                    **host_fields: Any) -> None:
        """Buffer one step's record. ``metrics`` values may be (and on the
        jitted paths are) un-fetched jax scalars; they sync only at the
        next drain."""
        if not self.enabled:
            return
        if self.watchdog is not None:
            # Heartbeat BEFORE the collect gate: non-collecting SPMD
            # processes still want hang detection.
            w = host_fields.get("wall_ms")
            self.watchdog.beat(float(w) / 1e3
                               if isinstance(w, (int, float)) else None)
        if not self._collect:
            return
        if len(self._ring) == self._ring.maxlen:
            self.dropped_records += 1
        self._ring.append((int(step), time.time(), dict(metrics),
                           host_fields))

    def heartbeat(self) -> None:
        """Manual watchdog beat for loops that are legitimately idle
        (the serving scheduler waiting on open-loop arrivals is not a
        hang)."""
        if self.watchdog is not None:
            self.watchdog.beat(None)

    def set_tap_spec(self, spec) -> None:
        """Arm NaN/Inf provenance: the engine hands over the TapSpec
        decoding its in-graph ``health_leaf_sq`` metric."""
        if self.health is not None:
            self.health.spec = spec

    def profiler_tick(self, step: int) -> None:
        if self.profiler is not None:
            self.profiler.tick(step)

    def _profiler_event(self, kind: str, payload: Dict[str, Any]) -> None:
        """ProfilerWindow outcome callback: every start/stop lands in the
        JSONL as a structured ``profile_window`` event (host IO only —
        no device access); a successful stop queues the capture for
        ingestion at the next report boundary."""
        self.event(kind, payload)
        if payload.get("phase") == "stop" and payload.get("ok"):
            self._profile_done.append(dict(payload))

    def arm_profile_window(self, num_steps: int,
                           start_step: Optional[int] = None
                           ) -> Optional[str]:
        """Arm a ``jax.profiler`` capture window over ``num_steps`` hot
        steps starting at ``start_step`` (default: the next step).
        Returns the capture dir, or None when refused (telemetry off, or
        a previously armed window hasn't finished — windows never
        clobber each other)."""
        if not self.enabled:
            return None
        p = self.profiler
        if p is not None and not p.failed and \
                (p._active or self.step_provider() < p.stop_step):
            logger.warning("telemetry: profile window already armed for "
                           f"steps [{p.start_step}, {p.stop_step}); "
                           "refusing to replace it")
            return None
        start = int(self.step_provider() + 1 if start_step is None
                    else start_step)
        self.profiler = ProfilerWindow(start, int(num_steps),
                                       self._profile_out,
                                       on_event=self._profiler_event)
        return self.profiler.capture_dir

    def _drain_profiles(self) -> None:
        """Report-boundary ingestion of completed capture windows: parse
        the trace, decompose the step wall into buckets, reconcile
        against the cost model when one is armed, and write one
        ``profile`` event (+ any ``reconcile_divergence`` events) per
        window. Pure host-side parsing — no device access."""
        done, self._profile_done = self._profile_done, []
        for win in done:
            from .profile_ingest import ingest
            n_steps = max(1, int(win.get("stop_step", 1))
                          - int(win.get("start_step", 0)))
            try:
                decomp = ingest(win["path"], n_steps=n_steps)
            except Exception as e:
                self.event("profile", {
                    "window": win,
                    "error": f"ingest failed ({type(e).__name__}: {e})"})
                continue
            payload: Dict[str, Any] = {"window": win,
                                       "decomposition": decomp}
            if self.cost_model_payload is not None and \
                    "error" not in decomp:
                from .reconcile import divergence_events, reconcile
                pc = getattr(self.cfg, "profile", None)
                recon = reconcile(
                    decomp, self.cost_model_payload,
                    threshold=getattr(pc, "divergence_threshold", 3.0),
                    host_frac=getattr(pc, "host_frac", 0.10))
                payload["reconciliation"] = recon
                self.event("profile", payload)
                for d in divergence_events(recon):
                    self.event("reconcile_divergence", d)
            else:
                self.event("profile", payload)

    def span(self, name: str, **args):
        """Host-span context manager. Feeds the trace writer (when a
        trace_path is set) and, for ``checkpoint_*`` spans, the goodput
        ledger's checkpoint bucket — outermost span only, so the
        pipeline engine's nested per-layer spans don't double-count.
        The async save path's ``checkpoint_snapshot`` span additionally
        files its wall under the ledger's ``checkpoint_snapshot``
        sub-figure — the exposed part of an async save."""
        bucket = "checkpoint" if name.startswith("checkpoint_") else None
        if self.tracer is None and (bucket is None or self.ledger is None):
            return nullcontext()
        sub = "checkpoint_snapshot" if name == "checkpoint_snapshot" \
            else None
        return self._span_ctx(name, bucket, args, sub=sub)

    @contextmanager
    def _span_ctx(self, name: str, bucket: Optional[str],
                  args: Dict[str, Any], sub: Optional[str] = None):
        outermost = False
        if bucket is not None and self.ledger is not None:
            outermost = self._ckpt_depth == 0
            self._ckpt_depth += 1
        t0 = time.perf_counter()
        try:
            if self.tracer is not None:
                with self.tracer.span(name, **args):
                    yield
            else:
                yield
        finally:
            if bucket is not None and self.ledger is not None:
                self._ckpt_depth -= 1
                if outermost:
                    self.ledger.note(bucket, time.perf_counter() - t0,
                                     sub=sub)

    def note_checkpoint_write_bg(self, seconds: float) -> None:
        """Background checkpoint-writer wall (called from the writer
        thread): reported in the ledger's overlapped ``checkpoint_write``
        figure, never charged against the window."""
        if self.ledger is not None:
            self.ledger.note_background("checkpoint_write", seconds)

    def add_span(self, name: str, t_start: float, dur_s: float,
                 args: Optional[Dict[str, Any]] = None) -> None:
        if self.tracer is not None:
            self.tracer.add_span(name, t_start, dur_s, args=args)

    def instrument_step_fn(self, name: str, fn: Callable) -> Callable:
        """Recompile-sentinel wrapping for a compiled step function;
        identity when telemetry is disabled. With the hang watchdog on,
        each dispatch also records the pending step signature (one
        attribute store) so a watchdog fire can name what the run was
        stuck on."""
        if self.sentinel is None:
            return fn
        wrapped = self.sentinel.instrument(name, fn)
        wd = self.watchdog
        if wd is None:
            return wrapped
        raw = getattr(wrapped, "__wrapped__", wrapped)

        @functools.wraps(wrapped)
        def with_pending(*args, **kwargs):
            wd.pending(name)
            return wrapped(*args, **kwargs)

        # Keep the RAW jitted fn reachable (flops profiler / hlo audit
        # unwrap via __wrapped__); functools.wraps would point it at the
        # sentinel wrapper instead.
        with_pending.__wrapped__ = raw
        return with_pending

    def raise_pending(self) -> None:
        """Surface a deferred fail_on_recompile violation (see
        RecompileSentinel.raise_pending — the raise must happen AFTER the
        caller stored the donated step's returned state)."""
        if self.sentinel is not None:
            self.sentinel.raise_pending()

    # ------------------------------------------------------------------ #
    # Offload trace synthesis: spans from the ALREADY-fenced per-bucket
    # timings run_bucketed_step measured — no new fences.
    # ------------------------------------------------------------------ #
    def add_offload_trace(self, timings: Dict[str, Any]) -> None:
        if self.tracer is None or not timings:
            return
        origin = timings.get("t_origin")
        pb = timings.get("per_bucket")
        t0s = timings.get("per_bucket_t0")
        if origin is None or not pb or not t0s:
            return
        phase_names = {"d2h_ms": "offload_d2h", "norm_ms": "offload_norm",
                       "adam_ms": "offload_adam", "h2d_ms": "offload_h2d"}
        for key, span_name in phase_names.items():
            starts = t0s.get(key.replace("_ms", "_t0"))
            durs = pb.get(key)
            if starts is None or durs is None:
                continue
            for b, (t0, ms) in enumerate(zip(starts, durs)):
                if ms <= 0.0:
                    continue
                self.tracer.add_span(f"{span_name} b{b}", origin + t0,
                                     ms / 1e3,
                                     tid=self.tracer.lane(span_name))

    # ------------------------------------------------------------------ #
    # Events (immediate write — rare, structured)
    # ------------------------------------------------------------------ #
    def event(self, kind: str, payload: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        # Meta must LEAD the stream: telemetry_report treats a meta
        # record as a new-run boundary and resets its accumulators, so
        # an event written before the first drain (an early recompile, a
        # serving request completing inside the first report window)
        # would otherwise be dropped from the summary.
        self._ensure_meta()
        rec = {"kind": "event", "event": kind,
               "step": int(self.step_provider()), "ts": time.time(),
               **payload}
        self.events.append(rec)
        self._write(rec)
        if self.flight is not None:
            self.flight.note_event(rec)
        if self.tracer is not None:
            self.tracer.instant(kind, args=payload)

    def _on_recompile(self, event: Dict[str, Any]) -> None:
        log_dist(
            f"telemetry: recompile of '{event['fn']}' after warmup "
            f"(compile #{event['total_compiles']}); signature delta: "
            + "; ".join(event["signature_delta"]), ranks=[0])
        self.event("recompile", event)

    @property
    def recompile_count(self) -> int:
        return self.sentinel.recompile_count if self.sentinel else 0

    # ------------------------------------------------------------------ #
    # Cost model (roofline + MFU) arming — report-boundary work
    # ------------------------------------------------------------------ #
    def set_cost_model(self, payload: Dict[str, Any],
                       samples_per_step: Optional[int] = None) -> None:
        """Record the built cost model (one ``cost_model`` JSONL record)
        and arm per-step MFU: subsequent drains stamp ``mfu`` onto every
        step record from its wall and the armed flops/peak — no extra
        device access (wall is already host data)."""
        if not self.enabled:
            return
        self.cost_model_payload = payload
        self._ensure_meta()
        self._write({"kind": "cost_model", "ts": time.time(), **payload})
        step = payload.get("step") or {}
        chip = payload.get("chip") or {}
        flops = float(step.get("flops_per_step") or 0.0)
        n_dev = int(payload.get("n_devices") or 1)
        try:
            peaks = ChipPeaks(**chip)
        except TypeError:
            return
        if flops > 0 and peaks.bf16_tflops > 0:
            self._mfu_arm = {
                "flops_per_step": flops,
                "peaks": peaks,
                "n_devices": n_dev,
                "samples_per_step": samples_per_step,
            }

    def _step_mfu(self, step_time_s: float) -> Optional[float]:
        """The shared MFU formula (cost_model.mfu) at the armed per-step
        flops/peak — one definition for per-step and window figures."""
        arm = self._mfu_arm
        if arm is None or step_time_s <= 0:
            return None
        return _mfu_formula(arm["flops_per_step"], step_time_s,
                            arm["n_devices"], arm["peaks"])

    # ------------------------------------------------------------------ #
    # Report boundary
    # ------------------------------------------------------------------ #
    def set_analytic_footprint(self, nbytes: int,
                               sampler: Optional[Callable] = None) -> None:
        """Arm the memory watermark with the analytic per-device
        model-state bytes (see monitor/memory.py)."""
        if not self.enabled or not self.cfg.memory_watermarks:
            return
        self.watermark = MemoryWatermark(
            nbytes, ratio=self.cfg.watermark_ratio,
            slack_bytes=self.cfg.watermark_slack_bytes,
            sampler=sampler or device_memory_stats)
        self.meta["analytic_state_bytes"] = int(nbytes)

    def maybe_drain(self, step: int,
                    extra: Optional[Dict[str, Any]] = None,
                    extra_fn: Optional[Callable[[], Dict[str, Any]]] = None
                    ) -> bool:
        """Drain iff ``step`` is a report boundary. ``extra_fn`` is only
        invoked when the drain fires — callers can defer work (e.g. a
        counter sync) that must not run on non-boundary steps."""
        if not self.enabled or step % self.report_steps != 0:
            return False
        if extra is None and extra_fn is not None:
            extra = extra_fn()
        self.drain(extra)
        return True

    def drain(self, extra: Optional[Dict[str, Any]] = None) -> None:
        """Flush the ring to JSONL: one batched device_get for every
        buffered scalar, then the memory sample + watermark check."""
        if not self.enabled:
            return
        self._ensure_meta()
        recs = list(self._ring)
        self._ring.clear()
        # One sync for the whole window.
        import jax
        pending = []
        for _, _, metrics, _ in recs:
            for v in metrics.values():
                if isinstance(v, jax.Array):
                    pending.append(v)
        fetched = iter(jax.device_get(pending)) if pending else iter(())
        step_infos = []
        anomaly_events: List[Dict[str, Any]] = []
        for step, ts, metrics, host_fields in recs:
            rec: Dict[str, Any] = {"kind": "step", "step": step, "ts": ts}
            for k, v in metrics.items():
                rec[k] = _to_py(next(fetched) if isinstance(v, jax.Array)
                                else v)
            for k, v in host_fields.items():
                rec[k] = _to_py(v) if not isinstance(v, dict) else v
            # MoE per-expert routed token counts ride as one [E] array
            # (fetched in the same batched device_get) — JSON-listify.
            moe_tokens = rec.get("moe_expert_tokens")
            if isinstance(moe_tokens, np.ndarray):
                rec["moe_expert_tokens"] = [
                    round(float(t), 2) for t in moe_tokens.reshape(-1)]
            # The in-graph health tap (already fetched in THE batched
            # device_get above) feeds provenance, not the JSONL record.
            leaf_sq = rec.pop(HEALTH_TAP_KEY, None)
            if self.health is not None:
                anomaly_events.extend(
                    self.health.check_step(step, rec, leaf_sq))
            wall_ms = rec.get("wall_ms")
            if isinstance(wall_ms, (int, float)):
                m = self._step_mfu(float(wall_ms) / 1e3)
                if m is not None:
                    # Per-step MFU from dispatch wall (see the wall_ms
                    # honesty note); the fenced figure is window_mfu.
                    # 4 significant digits, NOT fixed decimals — a tiny
                    # dev-model MFU (1e-10 on a CPU mesh) must stay
                    # nonzero.
                    rec["mfu"] = float(f"{m:.4g}")
            step_infos.append(extract_step_info(rec))
            self._write(rec)
            if self.flight is not None:
                self.flight.note_step(rec)
        # Anomaly events write AFTER the window's step records so the
        # stream stays chronologically readable; each names its step.
        for ev in anomaly_events:
            self.event("anomaly", ev)
        report: Dict[str, Any] = {
            "kind": "report", "step": int(self.step_provider()),
            "ts": time.time(), "records": len(recs),
            "dropped_records": self.dropped_records,
        }
        self.dropped_records = 0
        if extra:
            report.update({k: _to_py(v) if not isinstance(v, dict) else v
                           for k, v in extra.items()})
        if self._mfu_arm is not None and report.get("samples_per_sec_valid") \
                and report.get("samples_per_sec") \
                and self._mfu_arm.get("samples_per_step"):
            # Fenced window MFU: the throughput timer's synchronized
            # window average, not dispatch wall.
            step_time_s = self._mfu_arm["samples_per_step"] / \
                float(report["samples_per_sec"])
            m = self._step_mfu(step_time_s)
            if m is not None:
                report["window_mfu"] = float(f"{m:.4g}")
        if self.ledger is not None:
            if self.sentinel is not None:
                delta = self.sentinel.compile_wall_s - \
                    self._compile_wall_seen
                self._compile_wall_seen = self.sentinel.compile_wall_s
                self.ledger.note("recompile", delta)
            report["goodput"] = self.ledger.close_window(step_infos)
        if self.watermark is not None:
            stats, wm_event = self.watermark.check()
            report["memory"] = stats if stats is not None \
                else {"available": False}
            if wm_event is not None:
                logger.warning(
                    "telemetry: device memory watermark exceeded — peak "
                    f"{wm_event['peak_bytes_in_use_max'] / 2**30:.2f} GB vs "
                    f"analytic model-state "
                    f"{wm_event['analytic_state_bytes'] / 2**30:.2f} GB "
                    f"(x{wm_event['ratio']}); a sharding regression can "
                    "look exactly like this")
                self.event("memory_watermark", wm_event)
        self._write(report)
        if self._profile_done:
            self._drain_profiles()
        if self.flight is not None:
            self.flight.note_report(report)
        if self.tracer is not None:
            self.tracer.flush()

    def _ensure_meta(self) -> None:
        if self._meta_written:
            return
        self._meta_written = True
        self._write({"kind": "meta", "ts": time.time(), **self.meta})

    def _write(self, rec: Dict[str, Any]) -> None:
        if self.sink is not None:
            self.sink.write(rec)

    def _flight_snapshot(self) -> Dict[str, Any]:
        """Config/mesh/env snapshot for FLIGHT.json (host metadata only
        — callable from a signal handler)."""
        import platform
        import sys as _sys
        env: Dict[str, Any] = {"python": platform.python_version(),
                               "argv": list(_sys.argv)[:8],
                               "hostname": platform.node()}
        try:
            import jax
            env["jax"] = jax.__version__
            env["backend"] = jax.default_backend()
            env["local_devices"] = jax.local_device_count()
        except Exception:
            pass
        return {**{k: v for k, v in self.meta.items()
                   if not isinstance(v, (list, tuple)) or len(v) < 32},
                "env": env}

    # ------------------------------------------------------------------ #
    def close(self) -> None:
        if not self.enabled or self._closed:
            return
        # Mark closed FIRST: a signal handler landing on top of a
        # running close() (atexit already mid-drain when SIGTERM
        # arrives) must be a no-op re-entry, not a second drain.
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        # Stop a still-open capture window BEFORE the terminal drain so
        # its trace is ingested into this run's JSONL, not lost.
        if self.profiler is not None:
            self.profiler.stop()
        if self._ring or (self.ledger is not None
                          and self.ledger.has_pending()):
            # Drain buffered steps AND settle any trailing attributed
            # time (a checkpoint saved after the last report boundary
            # must not vanish from the goodput ledger).
            self.drain()
        else:
            self._ensure_meta()
        if self._profile_done:
            # A capture that completed after the last boundary (or whose
            # run had no further drain) still lands in the JSONL.
            self._drain_profiles()
        # Terminal drain marker: its absence is how the report tool
        # recognizes a truncated segment.
        self._write({"kind": "final", "step": int(self.step_provider()),
                     "ts": time.time()})
        if self.flight is not None:
            self.flight.closed_clean = True
            self.flight.persist("close")
            self.flight.uninstall()
        # Release process-lifetime anchors: the atexit hook keeps this
        # object (and anything its callbacks close over) alive, so a
        # closed Telemetry must unhook itself and drop the engine-side
        # step_provider closure — otherwise every engine ever built with
        # telemetry enabled pins its full device state until exit.
        atexit.unregister(self.close)
        self.step_provider = lambda: -1
        if self.tracer is not None:
            self.tracer.close()
        if self.sink is not None:
            self.sink.close()


__all__ = ["Telemetry", "JsonlSink", "analytic_state_bytes",
           "device_memory_stats"]
