"""Host-side span tracing: Chrome-trace/Perfetto JSON + jax.profiler window.

Spans are HOST wall-clock intervals (dispatch time, host Adam, D2H waits,
checkpoint IO) recorded with two ``perf_counter`` reads — never a device
fence. On the fused jitted paths the device-side phases (grad compute /
grad sync / optimizer apply) live inside one XLA program and are not
host-observable without fences; the honest device-side view is the
optional ``jax.profiler`` window (``ProfilerWindow``), which captures the
XLA execution trace for N configured steps.

The output is the Chrome Trace Event format ("traceEvents" array of
complete/instant events), loadable in Perfetto (ui.perfetto.dev) or
chrome://tracing.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

# Stable lane (tid) assignment so related spans stack in one row each in
# the Perfetto UI; unknown span names land in lane 0.
_LANES = {
    "train_batch": 0, "data_prep": 1, "step_dispatch": 2,
    "grad_compute": 2, "grad_sync": 3, "optimizer_apply": 4,
    "offload_step": 2, "offload_d2h": 3, "offload_norm": 4,
    "offload_adam": 5, "offload_h2d": 6,
    "checkpoint_save": 7, "checkpoint_load": 7,
}


class TraceWriter:
    """Chrome-trace writer in the JSON **array** format: events append to
    the file incrementally at each flush (the buffer then clears, so
    memory and per-flush IO stay O(events-since-last-flush), not
    O(run-length)); the array stays unterminated until ``close()``, which
    the trace format explicitly permits — a crashed run's partial file
    still loads in Perfetto. Non-writer processes buffer nothing."""

    def __init__(self, path: str, is_writer: Optional[bool] = None,
                 per_host: bool = False, rank: Optional[int] = None,
                 world: Optional[int] = None):
        # Shared writer resolution (monitor/hostinfo.py — the one copy
        # of the process-0 guard); with per_host, non-zero ranks write
        # their own ``<trace>.rankK.<ext>`` shard.
        from .hostinfo import resolve_writer, shard_path
        self.is_writer, self.rank, self.world = resolve_writer(
            is_writer, per_host=per_host, rank=rank, world=world)
        self.path = shard_path(path, self.rank if self.is_writer else 0)
        self._events: List[Dict[str, Any]] = []
        self._file = None
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self.closed = False

    # ------------------------------------------------------------------ #
    def _ts_us(self, t_abs: float) -> float:
        return (t_abs - self._t0) * 1e6

    def lane(self, name: str) -> int:
        return _LANES.get(name, 0)

    def add_span(self, name: str, t_start: float, dur_s: float,
                 tid: Optional[int] = None,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record a completed span from absolute ``perf_counter`` seconds."""
        if self.closed or not self.is_writer:
            return
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": self.lane(name) if tid is None else tid,
              "ts": self._ts_us(t_start), "dur": max(0.0, dur_s * 1e6)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, args: Optional[Dict[str, Any]] = None,
                t_abs: Optional[float] = None) -> None:
        if self.closed or not self.is_writer:
            return
        ev = {"name": name, "ph": "i", "s": "p", "pid": self._pid, "tid": 0,
              "ts": self._ts_us(time.perf_counter()
                                if t_abs is None else t_abs)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def flow(self, name: str, flow_id: int, phase: str, t_abs: float,
             tid: int = 0, cat: str = "request") -> None:
        """Flow-event arrow (ph ``s``/``t``/``f``) linking spans across
        lanes — Perfetto draws one arrow chain per ``flow_id`` (e.g. a
        request's route→admit→first-token across replica tracks)."""
        if self.closed or not self.is_writer:
            return
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        ev = {"name": name, "cat": cat, "ph": phase, "id": int(flow_id),
              "pid": self._pid, "tid": tid, "ts": self._ts_us(t_abs)}
        if phase == "f":
            ev["bp"] = "e"  # bind to the enclosing slice, not the next one
        with self._lock:
            self._events.append(ev)

    @contextmanager
    def span(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_span(name, t0, time.perf_counter() - t0,
                          args=args or None)

    # ------------------------------------------------------------------ #
    def flush(self) -> None:
        if not self.is_writer or self.closed:
            return
        with self._lock:
            events, self._events = self._events, []
        if not events:
            return
        if self._file is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(self.path, "w")
            self._file.write("[\n")
        for ev in events:
            self._file.write(json.dumps(ev) + ",\n")
        self._file.flush()

    def close(self) -> None:
        if self.closed:
            return
        self.flush()
        if self._file is not None:
            # Terminate the array with a sentinel (no trailing comma) so
            # the closed file is strict JSON; pre-close files are the
            # unterminated array form Perfetto accepts.
            self._file.write(json.dumps(
                {"name": "trace_end", "ph": "i", "s": "p",
                 "pid": self._pid, "tid": 0,
                 "ts": self._ts_us(time.perf_counter())}) + "]\n")
            self._file.close()
            self._file = None
        self.closed = True


class ProfilerWindow:
    """Capture a ``jax.profiler`` device trace for ``num_steps`` steps
    starting at ``start_step`` — the device-side complement to the host
    spans. ``tick(step)`` is two int compares on the hot path.

    Each window captures into its own ``step_<start>_<stop>`` suffix of
    ``out_dir`` so two windows in one run can never silently overwrite
    each other — a reused range is refused, not clobbered. Outcomes are
    surfaced as structured ``profile_window`` events through the
    ``on_event(kind, payload)`` callback (the telemetry JSONL), so
    downstream ingestion (monitor/profile_ingest.py) can locate the
    capture — or learn exactly why there isn't one — from the JSONL
    alone; log lines are a courtesy copy, not the record.
    """

    # Capture dirs claimed by any window in this process — the
    # same-out_dir uniqueness assert for satellite windows.
    _claimed_dirs: set = set()

    def __init__(self, start_step: int, num_steps: int, out_dir: str,
                 on_event=None):
        self.start_step = int(start_step)
        self.stop_step = int(start_step) + max(1, int(num_steps))
        self.out_dir = out_dir
        # Step-range suffix: the actual capture destination.
        self.capture_dir = os.path.join(
            out_dir, f"step_{self.start_step}_{self.stop_step}")
        self._on_event = on_event
        self._active = False
        self.failed = False

    def _emit(self, phase: str, ok: bool, reason: Optional[str] = None,
              **extra) -> None:
        payload = {"phase": phase, "path": self.capture_dir,
                   "start_step": self.start_step,
                   "stop_step": self.stop_step, "ok": bool(ok)}
        if reason is not None:
            payload["reason"] = reason
        payload.update(extra)
        if self._on_event is not None:
            try:
                self._on_event("profile_window", payload)
            except Exception as e:  # never take down the step loop
                logger.warning(f"telemetry: profile_window event emit "
                               f"failed ({type(e).__name__}: {e})")

    def _claim_dir(self) -> None:
        """Refuse a capture dir another window already used (in-process
        set) or that already holds a capture on disk (cross-process) —
        the silent-overwrite hazard."""
        if self.capture_dir in ProfilerWindow._claimed_dirs:
            raise RuntimeError(
                f"duplicate profile capture dir {self.capture_dir!r} "
                f"(a window for this step range already ran)")
        if os.path.isdir(self.capture_dir) and os.listdir(self.capture_dir):
            raise RuntimeError(
                f"profile capture dir {self.capture_dir!r} is not empty "
                f"(refusing to overwrite an existing capture)")
        ProfilerWindow._claimed_dirs.add(self.capture_dir)

    def tick(self, step: int) -> None:
        if self.failed:
            return
        # Range check, not equality: a run resumed from a checkpoint past
        # start_step (the first tick arrives mid-window or later) must
        # still capture whatever remains of the window instead of
        # silently never profiling.
        if not self._active and self.start_step <= step < self.stop_step:
            try:
                import jax
                self._claim_dir()
                os.makedirs(self.capture_dir, exist_ok=True)
                jax.profiler.start_trace(self.capture_dir)
                self._active = True
                self._emit("start", ok=True, armed_at_step=int(step))
            except Exception as e:
                self.failed = True
                reason = f"{type(e).__name__}: {e}"
                self._emit("start", ok=False, reason=reason)
                logger.warning(f"telemetry: jax.profiler trace failed to "
                               f"start ({reason})")
        elif self._active and step >= self.stop_step:
            self.stop()

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        try:
            import jax
            jax.profiler.stop_trace()
            self._emit("stop", ok=True)
            logger.info(f"telemetry: jax.profiler trace written to "
                        f"{self.capture_dir}")
        except Exception as e:
            self.failed = True
            reason = f"{type(e).__name__}: {e}"
            self._emit("stop", ok=False, reason=reason)
            logger.warning(f"telemetry: jax.profiler trace failed to stop "
                           f"({reason})")
