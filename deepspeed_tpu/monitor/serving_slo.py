"""Per-replica serving goodput ledger and SLO burn-rate tracking.

The training tier's GoodputLedger (monitor/goodput.py) enforces one
discipline: every wall-second lands in exactly one bucket and the
buckets sum to the wall.  This module applies the same discipline to a
serving replica, where the interesting split is not step/checkpoint/
stall but *what the replica's wall bought*:

- ``prefill``            — prompt ingestion (chunked or whole).
- ``decode_useful``      — decode/verify wall that emitted accepted
                           tokens (for speculative iterations, the
                           accepted-row share of the verify wall).
- ``spec_wasted``        — the drafted-but-rejected share of verify
                           wall: work the draft model caused that the
                           target model threw away.
- ``admission_blocked``  — the replica sat capacity-held: queued work
                           existed but the reservation gate / slot pool
                           refused admission and nothing else ran.
- ``idle``               — no queued work (open-loop arrival gaps).
- ``other``              — the residual (host loop overhead, and on the
                           CPU-mesh emulation: peer replicas' compute
                           interleaved on the same process).

``other`` is computed at settle time, never noted directly, so the
sum-to-wall identity holds by construction and the REAL check is the
``consistent`` flag: a residual below -1% of wall means double
attribution (the ledger invented time) and is surfaced, not clamped.

On top of the ledger, ``SLOTracker`` scores each completed request
against configurable TTFT/TPOT targets and computes attainment (the
fraction of requests inside target) plus the SRE burn rate: how fast
the error budget ``1 - availability_target`` is being consumed.
``burn_rate > 1`` means the budget will be exhausted before the window
does.

Everything here is host arithmetic on host-authoritative scheduler
state — zero device syncs, fence-asserted by tools/serve_slo_check.py.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

SERVING_BUCKETS = ("prefill", "decode_useful", "spec_wasted",
                   "admission_blocked", "idle")

# Residual tolerance: |negative residual| beyond this fraction of wall
# marks the ledger inconsistent (double-attributed time).
_TOL = 0.01


class ServingGoodputLedger:
    """Attribute a serving replica's wall to SERVING_BUCKETS + residual.

    Buckets are measured independently (each caller notes the wall it
    directly measured); ``snapshot(wall_s)`` settles the residual into
    ``other`` and flags over-attribution instead of hiding it.
    """

    def __init__(self, label: Optional[str] = None, clock=time.perf_counter):
        self.label = label
        self._clock = clock
        self.t0 = clock()
        self._noted: Dict[str, float] = {b: 0.0 for b in SERVING_BUCKETS}

    def note(self, bucket: str, seconds: float) -> None:
        """Attribute ``seconds`` of directly-measured wall to ``bucket``."""
        if bucket not in self._noted:
            raise ValueError(
                f"unknown serving bucket {bucket!r}; "
                f"expected one of {SERVING_BUCKETS}")
        if seconds > 0:
            self._noted[bucket] += float(seconds)

    def reset(self) -> None:
        self.t0 = self._clock()
        for b in self._noted:
            self._noted[b] = 0.0

    def noted_total(self) -> float:
        return sum(self._noted.values())

    def snapshot(self, wall_s: Optional[float] = None) -> dict:
        """Settle against ``wall_s`` (default: elapsed since construction).

        Non-destructive: callers can snapshot at every report boundary
        and again at serve end.
        """
        wall = float(wall_s) if wall_s is not None else self._clock() - self.t0
        noted = self.noted_total()
        other = wall - noted
        tol = _TOL * max(wall, 1e-9)
        out: dict = {"wall_s": wall}
        if self.label:
            out["label"] = self.label
        for b in SERVING_BUCKETS:
            out[f"{b}_s"] = self._noted[b]
        # other is the residual: the identity sum(buckets)+other == wall
        # holds by construction; a residual below -1% of wall means the
        # measured buckets overlap (double attribution) — surfaced, not
        # clamped.
        out["other_s"] = other
        out["accounted_fraction"] = (noted + max(other, 0.0)) / max(wall, 1e-9)
        out["consistent"] = bool(other >= -tol)
        return out

    @classmethod
    def merged(cls, snapshots: Sequence[dict]) -> dict:
        """Pool per-replica ledger snapshots (bucket-wise sums).

        Walls sum too: on the CPU-mesh emulation replicas interleave on
        one process so the merged wall double-counts real time — honest
        for bucket *shares*, not absolute fleet wall.
        """
        snaps = [s for s in snapshots if isinstance(s, dict)]
        out: dict = {"wall_s": sum(float(s.get("wall_s", 0.0)) for s in snaps),
                     "replicas": len(snaps)}
        noted = 0.0
        for b in SERVING_BUCKETS:
            tot = sum(float(s.get(f"{b}_s", 0.0)) for s in snaps)
            out[f"{b}_s"] = tot
            noted += tot
        other = out["wall_s"] - noted
        out["other_s"] = other
        out["accounted_fraction"] = ((noted + max(other, 0.0))
                                     / max(out["wall_s"], 1e-9))
        out["consistent"] = all(bool(s.get("consistent", True)) for s in snaps)
        return out


class SLOTracker:
    """Windowed SLO attainment + error-budget burn rate.

    A completed request is *good* when its TTFT and TPOT are both
    inside target (an unset target — 0 — always passes).  Aborted or
    starved-to-death requests count as bad via ``observe_failure``.

    - attainment  = good / total
    - error budget = 1 - availability_target
    - burn_rate   = (1 - attainment) / error_budget
      (> 1: the budget is being consumed faster than the SLO allows).

    ``windowed`` recomputes both over the trailing ``window_s`` seconds
    so a burst of misses is visible before the cumulative numbers move.
    """

    def __init__(self, ttft_ms: float = 0.0, tpot_ms: float = 0.0,
                 availability: float = 0.99, window_s: float = 60.0,
                 clock=time.perf_counter):
        if not (0.0 < availability < 1.0):
            raise ValueError("availability target must be in (0, 1)")
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.ttft_ms = float(ttft_ms)
        self.tpot_ms = float(tpot_ms)
        self.availability = float(availability)
        self.window_s = float(window_s)
        self._clock = clock
        # (t, good) per outcome; pruned lazily against window_s.
        self._outcomes: List[tuple] = []
        self.total = 0
        self.good = 0
        self.ttft_misses = 0
        self.tpot_misses = 0
        self.failures = 0

    @property
    def enabled(self) -> bool:
        return self.ttft_ms > 0 or self.tpot_ms > 0

    def observe(self, ttft_s: Optional[float], tpot_s: Optional[float],
                t: Optional[float] = None) -> bool:
        """Score one completed request; returns whether it met the SLO."""
        good = True
        if self.ttft_ms > 0 and ttft_s is not None \
                and ttft_s * 1e3 > self.ttft_ms:
            good = False
            self.ttft_misses += 1
        if self.tpot_ms > 0 and tpot_s is not None \
                and tpot_s * 1e3 > self.tpot_ms:
            good = False
            self.tpot_misses += 1
        self.total += 1
        if good:
            self.good += 1
        self._outcomes.append((t if t is not None else self._clock(), good))
        return good

    def observe_failure(self, t: Optional[float] = None) -> None:
        """An aborted / never-served request: counts against availability."""
        self.total += 1
        self.failures += 1
        self._outcomes.append((t if t is not None else self._clock(), False))

    def _burn(self, good: int, total: int) -> dict:
        att = good / total if total else None
        budget = 1.0 - self.availability
        burn = None if att is None else (1.0 - att) / max(budget, 1e-9)
        return {"attainment": att, "burn_rate": burn}

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else self._clock()
        cutoff = now - self.window_s
        w = [(t, g) for (t, g) in self._outcomes if t >= cutoff]
        self._outcomes = w  # lazy prune
        out = {
            "targets": {"ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                        "availability": self.availability,
                        "window_s": self.window_s},
            "total": self.total,
            "good": self.good,
            "ttft_misses": self.ttft_misses,
            "tpot_misses": self.tpot_misses,
            "failures": self.failures,
        }
        out.update(self._burn(self.good, self.total))
        wg = sum(1 for (_, g) in w if g)
        out["window"] = {"n": len(w)}
        out["window"].update(self._burn(wg, len(w)))
        return out

    @classmethod
    def merged(cls, trackers: Sequence["SLOTracker"]) -> Optional[dict]:
        """Fleet-level snapshot: pool outcomes across replica trackers.

        Targets are taken from the first tracker (the fleet shares one
        SLO); window attainment pools each tracker's trailing window.
        """
        live = [t for t in trackers if t is not None and t.enabled]
        if not live:
            return None
        base = live[0]
        out = {
            "targets": {"ttft_ms": base.ttft_ms, "tpot_ms": base.tpot_ms,
                        "availability": base.availability,
                        "window_s": base.window_s},
            "replicas": len(live),
            "total": sum(t.total for t in live),
            "good": sum(t.good for t in live),
            "ttft_misses": sum(t.ttft_misses for t in live),
            "tpot_misses": sum(t.tpot_misses for t in live),
            "failures": sum(t.failures for t in live),
        }
        out.update(base._burn(out["good"], out["total"]))
        now = base._clock()
        wn = wg = 0
        for t in live:
            cutoff = now - t.window_s
            w = [(ts, g) for (ts, g) in t._outcomes if ts >= cutoff]
            wn += len(w)
            wg += sum(1 for (_, g) in w if g)
        out["window"] = {"n": wn}
        out["window"].update(base._burn(wg, wn))
        return out
