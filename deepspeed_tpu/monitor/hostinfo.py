"""Shared SPMD process-identity resolver for the monitor subsystem.

``JsonlSink`` and ``TraceWriter`` each used to carry a private copy of
the ``is_writer = jax.process_index() == 0`` guard; any drift between
them (one honoring an override, the other not) silently forks the
question "who writes files?". This module is the ONE answer, and the
per-host telemetry shards (``telemetry.per_host_shards``) build on the
same resolver: rank 0 writes the primary stream, rank K writes
``<name>.rankK.<ext>`` when sharding is on, and everyone else writes
nothing — explicitly, with a logged notice instead of a silent drop.

``DS_PROC_INDEX`` / ``DS_PROC_COUNT`` override the jax-reported identity
(test/bench hook: exercising the multi-host shard + aggregation path on
a single-process CPU mesh without a real pod). ``DS_NUM_SLICES`` layers
the multi-slice topology on top: processes enumerate slice-major (slice
0's hosts first — matching the mesh's outermost ``slice`` axis), so
``slice_identity()`` maps the flat process index to (slice_id,
rank-in-slice) and the two-slice emulated world is just
DS_PROC_COUNT=4 DS_NUM_SLICES=2 over four single-host invocations.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple


def process_identity() -> Tuple[int, int]:
    """(process_index, process_count) — env override first, then jax,
    then the single-process fallback (jax not importable / backend
    dead, e.g. inside a crashing signal handler)."""
    env_idx = os.environ.get("DS_PROC_INDEX")
    if env_idx is not None:
        return int(env_idx), int(os.environ.get("DS_PROC_COUNT",
                                                int(env_idx) + 1))
    try:
        import jax
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def slice_identity(num_slices: Optional[int] = None
                   ) -> Tuple[int, int, int]:
    """(slice_id, rank_in_slice, num_slices) for this process.

    ``num_slices`` defaults to ``$DS_NUM_SLICES`` (1 when unset — the
    single-slice world every pre-multislice consumer assumed). Processes
    enumerate slice-major: with P processes and S slices, process p sits
    in slice ``p // (P/S)`` at in-slice rank ``p % (P/S)`` — the same
    outermost-slice order ``build_mesh(slices=...)`` lays devices out
    in. A process count not divisible by the slice count is a topology
    error, said plainly."""
    rank, world = process_identity()
    if num_slices is None:
        num_slices = int(os.environ.get("DS_NUM_SLICES", "1"))
    if num_slices <= 1:
        return 0, rank, 1
    if world % num_slices != 0:
        raise ValueError(
            f"process count {world} not divisible by num_slices="
            f"{num_slices} (DS_NUM_SLICES): every slice must hold the "
            "same number of hosts")
    per_slice = world // num_slices
    return rank // per_slice, rank % per_slice, num_slices


def resolve_writer(is_writer: Optional[bool] = None,
                   per_host: bool = False,
                   rank: Optional[int] = None,
                   world: Optional[int] = None
                   ) -> Tuple[bool, int, int]:
    """(writes_a_file, rank, world). An explicit ``is_writer`` wins (the
    historical injection point tests use); otherwise rank 0 always
    writes, and other ranks write their own shard iff ``per_host``."""
    if rank is None:
        rank, world = process_identity()
    elif world is None:
        world = rank + 1
    if is_writer is None:
        is_writer = rank == 0 or per_host
    return bool(is_writer), int(rank), int(world)


def shard_path(path: str, rank: int) -> str:
    """Per-host shard name: ``runs/job.jsonl`` -> ``runs/job.rank3.jsonl``
    for rank 3; rank 0 keeps the primary path (so single-host runs and
    every existing consumer see the same file they always did)."""
    if rank == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{rank}{ext}"


__all__ = ["process_identity", "slice_identity", "resolve_writer",
           "shard_path"]
