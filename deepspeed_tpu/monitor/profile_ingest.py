"""XLA profile-trace ingestion: per-op records and step wall decomposition.

Parses a captured ``jax.profiler`` trace directory (the gzipped
Chrome-trace JSON that ``jax.profiler.start_trace``/``stop_trace`` write
under ``<dir>/plugins/profile/<timestamp>/<host>.trace.json.gz``) — or any
trace-event JSON, including ``monitor/trace.py::TraceWriter``'s
incremental array form — into structured :class:`OpRecord` rows, then
classifies every device op into one of the measurement buckets:

``gemm``
    MXU/GEMM work: ``dot``/``convolution`` HLOs and fusions rooted in them.
``pallas``
    Our Pallas custom kernels, recognized by kernel name (fused LN/GELU,
    flash attention fwd/bwd, grouped-GEMM MoE, paged attention, fused
    optimizer update, sparse flash).
``collective_ici`` / ``collective_dcn``
    Cross-device collectives, split by tier with the
    ``parallel/axis_algebra.py`` vocabulary: an op naming a DCN axis
    (``DCN_AXES``, e.g. ``slice``) or an explicit dcn channel marker is
    DCN wire; every other collective is intra-slice ICI.
``host``
    Host transfers and host-visible stalls: D2H/H2D copies,
    infeed/outfeed, ``TfrtCpuBuffer::Await``-style blocking waits.
``unattributed``
    Device-lane busy time we could not classify. Surfaced as its own
    bucket — never clamped, never folded into the others — so a
    decomposition that fails to explain the wall says so.

plus the derived ``idle`` gap (window wall not covered by any device-lane
op). The decomposition is a sweep line over the merged device-lane
intervals with a fixed bucket priority (dcn > ici > host > pallas > gemm
> unattributed), so buckets + idle partition the profiled window span
exactly; the per-step wall is the window span divided by the number of
profiled steps.

Pure host-side parsing: no jax import on the hot path, no device work.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..parallel.axis_algebra import DCN_AXES

__all__ = [
    "OpRecord", "BUCKETS", "BUCKET_PRIORITY", "PALLAS_KERNEL_PATTERNS",
    "find_trace_files", "parse_trace_events", "load_trace_events",
    "classify_op", "ingest_events", "ingest", "ingest_from_telemetry",
]

# Decomposition buckets, in sweep-line priority order: when two device
# ops overlap in time, the higher-priority bucket owns the overlap (a
# collective overlapping a GEMM is deliberate comm/compute overlap — the
# wire time is the scarce resource being measured).
BUCKET_PRIORITY: Tuple[str, ...] = (
    "collective_dcn", "collective_ici", "host", "pallas", "gemm",
    "unattributed",
)
BUCKETS: Tuple[str, ...] = BUCKET_PRIORITY + ("idle",)

# Pallas kernels shipped in ops/ — matched against the op/kernel name.
# Keys are the friendly family names that show up in reports.
PALLAS_KERNEL_PATTERNS: Dict[str, str] = {
    "fused_ln": r"_ln_(fwd|bwd)_kernel|fused_layer_norm",
    "fused_gelu": r"_gelu_(fwd|bwd)_kernel|fused_gelu",
    "sparse_flash": (r"_sfwd_kernel|_sdq_kernel|_sdkv_kernel"
                     r"|_sfused_bwd_kernel|sparse_flash"),
    "flash_attention": (r"flash|_fwd_kernel|_bwd_dq_kernel|_bwd_dkv_kernel"
                        r"|_bwd_fused_kernel"),
    "grouped_gemm": r"_gg_kernel|grouped_gemm",
    "paged_attention": r"_pattn_kernel|paged_att",
    "fused_update": r"_fused_adam_kernel|_sqnorm_kernel|fused_update",
}
_PALLAS_RE = {k: re.compile(v) for k, v in PALLAS_KERNEL_PATTERNS.items()}

# HLO/op-name classifiers. Order matters only within classify_op below.
_GEMM_RE = re.compile(r"^(dot|convolution|cublas|gemm)\b|\bdot_general\b")
_COLLECTIVE_RE = re.compile(
    r"all-reduce|all_reduce|allreduce|all-gather|all_gather|allgather"
    r"|reduce-scatter|reduce_scatter|all-to-all|all_to_all|alltoall"
    r"|collective-permute|collective_permute|ppermute|psum\b|pmean\b")
_HOST_RE = re.compile(
    r"\bcopy[-_ ]?(start|done)?\b|d2h|h2d|device[-_ ]?to[-_ ]?host"
    r"|host[-_ ]?to[-_ ]?device|infeed|outfeed|transfer"
    r"|TfrtCpuBuffer::Await|BlockHostUntilReady|SyncAllActivity",
    re.IGNORECASE)
# Runtime container spans that wrap whole programs/regions rather than
# naming one op (XLA:CPU's executor scaffolding, pjit python frames).
# Counting them as busy time would double-cover every real op below
# them, so an otherwise-unclassifiable event matching this is dropped
# from attribution — the real ops it contains are attributed directly.
_SCAFFOLD_RE = re.compile(
    r"TaskDispatcher|ThunkExecutor|ExecuteHelper|TfrtCpuExecutable"
    r"|ExecuteOnStream|XlaModule|PjitFunction|jit_|ProgramRegion"
    r"|ThreadpoolListener|RunToCompletion")
# Markers that put a collective on the DCN tier: an explicit dcn tag or
# any DCN axis name (axis_algebra.DCN_AXES) in the op name / args.
_DCN_MARKER_RE = re.compile(
    r"\bdcn\b|" + "|".join(rf"\b{re.escape(a)}\b" for a in DCN_AXES),
    re.IGNORECASE)


@dataclass
class OpRecord:
    """One complete (``ph == "X"``) trace event, bucket-classified."""
    name: str
    bucket: str
    pid: int
    tid: int
    ts_us: float
    dur_us: float
    kernel_family: Optional[str] = None  # set for bucket == "pallas"
    args: Dict[str, Any] = field(default_factory=dict)

    @property
    def end_us(self) -> float:
        return self.ts_us + self.dur_us


# --------------------------------------------------------------------- #
# Trace discovery + parsing
# --------------------------------------------------------------------- #
def find_trace_files(trace_dir: str) -> List[str]:
    """All trace-event JSON files under ``trace_dir``, newest profile
    session first. Understands the ``jax.profiler`` layout
    (``plugins/profile/<ts>/*.trace.json.gz``) and bare ``*.json`` /
    ``*.json.gz`` drops (e.g. a TraceWriter host trace)."""
    if not trace_dir or not os.path.isdir(trace_dir):
        return []
    hits: List[str] = []
    for pat in ("plugins/profile/*/*.trace.json.gz",
                "plugins/profile/*/*.trace.json",
                "*.trace.json.gz", "*.trace.json", "*.json.gz", "*.json"):
        hits.extend(glob.glob(os.path.join(trace_dir, pat)))
    # De-dup, newest mtime first so the latest capture wins.
    uniq = sorted(set(hits), key=lambda p: (-os.path.getmtime(p), p))
    return uniq


def parse_trace_events(text: str) -> List[Dict[str, Any]]:
    """Parse trace-event JSON in any of the forms we produce or consume:

    * dict form ``{"traceEvents": [...], ...}`` (jax.profiler),
    * strict JSON array ``[...]`` (closed TraceWriter file),
    * unterminated array form ``[\\n{...},\\n{...},\\n`` (TraceWriter
      before ``close()`` — the crash-tolerant form Perfetto accepts).
    """
    text = text.strip()
    if not text:
        return []
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # Unterminated array form: strip the trailing comma, close it.
        repaired = text.rstrip().rstrip(",")
        if not repaired.startswith("["):
            raise
        doc = json.loads(repaired + "]")
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError(f"unrecognized trace JSON root: {type(doc).__name__}")
    return [e for e in events if isinstance(e, dict)]


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Read one trace file (gzip-aware) into a raw event list."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return parse_trace_events(f.read())
    with open(path) as f:
        return parse_trace_events(f.read())


# --------------------------------------------------------------------- #
# Classification
# --------------------------------------------------------------------- #
def _pallas_family(text: str) -> Optional[str]:
    for family, rx in _PALLAS_RE.items():
        if rx.search(text):
            return family
    return None


def classify_op(name: str, args: Optional[Dict[str, Any]] = None
                ) -> Tuple[str, Optional[str]]:
    """Map an op/event name (+ args) to ``(bucket, kernel_family)``.

    The HLO op name (``args["hlo_op"]``, e.g. ``dot.5``) is preferred
    over the event display name when present — fusions keep the root
    op's identity there.
    """
    args = args or {}
    hlo_op = str(args.get("hlo_op", "") or "")
    probe = f"{name} {hlo_op} {args.get('hlo_module', '')}"
    low = probe.lower()
    fam = _pallas_family(probe)
    # Pallas kernels surface as custom-calls named after the kernel fn;
    # the name match alone is the signal (unless it also looks like a
    # collective, which wins).
    if fam is not None and _COLLECTIVE_RE.search(low) is None:
        return "pallas", fam
    if _COLLECTIVE_RE.search(low):
        tier = "dcn" if _DCN_MARKER_RE.search(probe) else "ici"
        return f"collective_{tier}", None
    if _HOST_RE.search(probe):
        return "host", None
    target = hlo_op or name
    if _GEMM_RE.search(target) or _GEMM_RE.search(
            target.split("(")[0].strip()):
        return "gemm", None
    if target.startswith("fusion") and "dot" in low:
        return "gemm", None
    return "unattributed", None


def _thread_meta(events: Iterable[Dict[str, Any]]
                 ) -> Dict[Tuple[int, int], str]:
    """(pid, tid) → thread name from the metadata (``ph == "M"``) events."""
    names: Dict[Tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[(int(e.get("pid", 0)), int(e.get("tid", 0)))] = str(
                (e.get("args") or {}).get("name", ""))
    return names


def _device_lanes(events: List[Dict[str, Any]],
                  thread_names: Dict[Tuple[int, int], str]
                  ) -> set:
    """Lanes carrying device-op execution: any (pid, tid) with at least
    one complete event bearing an ``hlo_op``/``hlo_module`` arg, plus
    lanes whose thread name marks an XLA/TPU device stream."""
    lanes = set()
    for e in events:
        if e.get("ph") != "X":
            continue
        a = e.get("args") or {}
        if "hlo_op" in a or "hlo_module" in a:
            lanes.add((int(e.get("pid", 0)), int(e.get("tid", 0))))
    dev_re = re.compile(r"(?i)xla|tpu|/device:|stream|tensorflow ops")
    for key, nm in thread_names.items():
        if dev_re.search(nm) and "python" not in nm.lower():
            lanes.add(key)
    return lanes


# --------------------------------------------------------------------- #
# Decomposition
# --------------------------------------------------------------------- #
_PRIO = {b: i for i, b in enumerate(BUCKET_PRIORITY)}


def _sweep(records: List[OpRecord]) -> Dict[str, float]:
    """Sweep-line attribution: for every elementary time segment inside
    the window, the highest-priority active bucket owns it. Returns
    per-bucket microseconds (no idle — the caller derives it from the
    window span). Buckets partition covered time exactly by construction.
    """
    walls = {b: 0.0 for b in BUCKET_PRIORITY}
    if not records:
        return walls
    # Boundary events: (+1 at start, -1 at end) per bucket.
    points: List[Tuple[float, int, int]] = []  # (t, delta, prio)
    for r in records:
        if r.dur_us <= 0:
            continue
        p = _PRIO[r.bucket]
        points.append((r.ts_us, +1, p))
        points.append((r.end_us, -1, p))
    if not points:
        return walls
    points.sort(key=lambda t: (t[0], -t[1]))
    active = [0] * len(BUCKET_PRIORITY)
    prev_t = points[0][0]
    for t, delta, prio in points:
        if t > prev_t:
            seg = t - prev_t
            for i, b in enumerate(BUCKET_PRIORITY):
                if active[i] > 0:
                    walls[b] += seg
                    break
            prev_t = t
        active[prio] += delta
    return walls


def ingest_events(events: List[Dict[str, Any]], n_steps: int = 1,
                  top_k: int = 12) -> Dict[str, Any]:
    """Classify + decompose one raw event list. See :func:`ingest`."""
    thread_names = _thread_meta(events)
    lanes = _device_lanes(events, thread_names)
    records: List[OpRecord] = []
    n_span_events = 0
    for e in events:
        if e.get("ph") != "X":
            continue
        n_span_events += 1
        key = (int(e.get("pid", 0)), int(e.get("tid", 0)))
        if lanes and key not in lanes:
            continue
        args = e.get("args") or {}
        name = str(e.get("name", ""))
        bucket, fam = classify_op(name, args)
        if bucket == "unattributed" and _SCAFFOLD_RE.search(name):
            continue
        records.append(OpRecord(
            name=name, bucket=bucket, pid=key[0], tid=key[1],
            ts_us=float(e.get("ts", 0.0)), dur_us=float(e.get("dur", 0.0)),
            kernel_family=fam, args=args))
    if records:
        t0 = min(r.ts_us for r in records)
        t1 = max(r.end_us for r in records)
        wall_us = max(0.0, t1 - t0)
    else:
        wall_us = 0.0
    walls_us = _sweep(records)
    covered_us = sum(walls_us.values())
    idle_us = max(0.0, wall_us - covered_us)
    n = max(1, int(n_steps))

    buckets_ms = {b: round(v / 1e3, 6) for b, v in walls_us.items()}
    buckets_ms["idle"] = round(idle_us / 1e3, 6)
    per_step_ms = {b: round(v / n, 6) for b, v in buckets_ms.items()}
    # Explained fraction: buckets + idle vs the window wall. With a
    # non-degenerate window this is 1.0 by construction (the sweep
    # partitions covered time; idle is the complement); the residual
    # only moves when records are empty or clocks are inconsistent.
    total_ms = round(sum(buckets_ms.values()), 6)
    wall_ms = round(wall_us / 1e3, 6)

    by_bucket_count: Dict[str, int] = {b: 0 for b in BUCKET_PRIORITY}
    op_dur: Dict[Tuple[str, str], float] = {}
    fam_dur: Dict[str, float] = {}
    for r in records:
        by_bucket_count[r.bucket] += 1
        base = re.sub(r"[.\d]+$", "", r.args.get("hlo_op", r.name)
                      if isinstance(r.args.get("hlo_op"), str) else r.name)
        k = (r.bucket, base or r.name)
        op_dur[k] = op_dur.get(k, 0.0) + r.dur_us
        if r.kernel_family:
            fam_dur[r.kernel_family] = (fam_dur.get(r.kernel_family, 0.0)
                                        + r.dur_us)
    top_ops = [
        {"bucket": b, "op": op, "total_ms": round(us / 1e3, 6)}
        for (b, op), us in sorted(op_dur.items(), key=lambda kv: -kv[1])
    ][:top_k]
    return {
        "n_events": n_span_events,
        "n_device_ops": len(records),
        "n_device_lanes": len(lanes),
        "steps": n,
        "wall_ms": wall_ms,
        "per_step_wall_ms": round(wall_ms / n, 6),
        "buckets_ms": buckets_ms,
        "per_step_ms": per_step_ms,
        "pallas_families_ms": {k: round(v / 1e3, 6)
                               for k, v in sorted(fam_dur.items())},
        "bucket_op_counts": by_bucket_count,
        "top_ops": top_ops,
        "sum_check": {
            "decomposed_ms": total_ms,
            "wall_ms": wall_ms,
            "explained_frac": round(total_ms / wall_ms, 6) if wall_ms else 1.0,
            "unattributed_ms": buckets_ms["unattributed"],
        },
    }


def ingest(trace_dir: str, n_steps: int = 1, top_k: int = 12
           ) -> Dict[str, Any]:
    """Ingest every trace file of the newest capture under ``trace_dir``.

    Returns the decomposition summary (see :func:`ingest_events`) with a
    ``trace_files`` listing; multiple hosts' shards from the same
    ``plugins/profile/<ts>`` session are merged into one timeline
    (profiler timestamps share one clock per session).
    """
    files = find_trace_files(trace_dir)
    if not files:
        return {"error": f"no trace files under {trace_dir!r}",
                "trace_files": [], "n_device_ops": 0}
    # Keep only files from the newest jax.profiler session when the
    # plugins/ layout is present; otherwise take the newest file.
    sessions = [f for f in files if os.sep + "plugins" + os.sep in f]
    if sessions:
        newest_dir = os.path.dirname(sessions[0])
        chosen = [f for f in sessions if os.path.dirname(f) == newest_dir]
    else:
        chosen = [files[0]]
    events: List[Dict[str, Any]] = []
    for f in chosen:
        events.extend(load_trace_events(f))
    out = ingest_events(events, n_steps=n_steps, top_k=top_k)
    out["trace_files"] = [os.path.relpath(f, trace_dir) for f in chosen]
    out["trace_dir"] = trace_dir
    return out


def ingest_from_telemetry(jsonl_path: str, top_k: int = 12
                          ) -> Dict[str, Any]:
    """Locate the capture from the telemetry JSONL alone: read the
    ``profile_window`` event (written by ``ProfilerWindow``) for the
    trace path and step range, then :func:`ingest` it."""
    win: Optional[Dict[str, Any]] = None
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (rec.get("kind") == "event"
                    and rec.get("event") == "profile_window"
                    and rec.get("phase") == "stop"):
                # Telemetry events splat their payload into the record.
                win = {k: rec[k] for k in ("phase", "path", "start_step",
                                           "stop_step", "ok", "reason")
                       if k in rec}
    if win is None:
        return {"error": "no completed profile_window event in "
                         f"{jsonl_path!r}", "n_device_ops": 0}
    if not win.get("ok", False):
        return {"error": "profile window failed: "
                         f"{win.get('reason', 'unknown')}",
                "profile_window": win, "n_device_ops": 0}
    n_steps = max(1, int(win.get("stop_step", 1)) - int(
        win.get("start_step", 0)))
    out = ingest(win["path"], n_steps=n_steps, top_k=top_k)
    out["profile_window"] = win
    return out
