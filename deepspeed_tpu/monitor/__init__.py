"""monitor/ — the unified telemetry subsystem.

First-class operational visibility for TPU training runs: structured
per-step records (ring-buffered, drained to JSONL at report boundaries
with zero added hot-path syncs), host-side Chrome-trace spans, a
recompile sentinel over the engine's compiled step functions,
device-memory watermarks checked against the analytic ZeRO-partitioned
model-state footprint, a roofline cost model fusing XLA's compiled cost
analysis with the jaxpr-walk flops profiler and the interconnect wire
model (per-path compute/HBM/interconnect-bound verdicts + per-step MFU),
a goodput ledger attributing every wall-clock second between report
boundaries, and the measured half of the roofline story: jax.profiler
trace ingestion into a bucketed per-step wall decomposition
(profile_ingest) reconciled against the analytic floors (reconcile).
See docs/tutorials/telemetry.md.
"""
from .cost_model import (BOUND_COMPUTE, BOUND_HBM, BOUND_INTERCONNECT,
                         build_cost_model, mfu, roofline)
from .flight import FlightRecorder
from .goodput import BUCKETS as GOODPUT_BUCKETS
from .goodput import GoodputLedger
from .health import (EwmaDetector, HangWatchdog, HealthMonitor, TapSpec,
                     leaf_sq_taps)
from .hostinfo import process_identity, resolve_writer, shard_path
from .memory import (MemoryWatermark, analytic_state_bytes,
                     device_memory_stats)
from .peaks import (TPU_PEAK_TFLOPS, ChipPeaks, chip_peak_tflops,
                    chip_peaks)
from .profile_ingest import (ingest, ingest_from_telemetry,
                             parse_trace_events)
from .recompile import RecompileError, RecompileSentinel
from .reconcile import reconcile
from .request_trace import RequestTrace, validate_timeline
from .serving import ServingAggregator
from .serving_slo import (SERVING_BUCKETS, ServingGoodputLedger, SLOTracker)
from .telemetry import JsonlSink, Telemetry
from .trace import ProfilerWindow, TraceWriter

__all__ = [
    "Telemetry", "JsonlSink", "TraceWriter", "ProfilerWindow",
    "RecompileSentinel", "RecompileError", "MemoryWatermark",
    "analytic_state_bytes", "device_memory_stats",
    "GoodputLedger", "GOODPUT_BUCKETS", "ServingAggregator",
    "ServingGoodputLedger", "SLOTracker", "SERVING_BUCKETS",
    "RequestTrace", "validate_timeline",
    "HealthMonitor", "EwmaDetector", "HangWatchdog", "TapSpec",
    "leaf_sq_taps", "FlightRecorder",
    "process_identity", "resolve_writer", "shard_path",
    "build_cost_model", "roofline", "mfu",
    "ingest", "ingest_from_telemetry", "parse_trace_events", "reconcile",
    "BOUND_COMPUTE", "BOUND_HBM", "BOUND_INTERCONNECT",
    "ChipPeaks", "chip_peaks", "chip_peak_tflops", "TPU_PEAK_TFLOPS",
]
