"""monitor/ — the unified telemetry subsystem.

First-class operational visibility for TPU training runs: structured
per-step records (ring-buffered, drained to JSONL at report boundaries
with zero added hot-path syncs), host-side Chrome-trace spans, a
recompile sentinel over the engine's compiled step functions, and
device-memory watermarks checked against the analytic ZeRO-partitioned
model-state footprint. See docs/tutorials/telemetry.md.
"""
from .memory import (MemoryWatermark, analytic_state_bytes,
                     device_memory_stats)
from .recompile import RecompileError, RecompileSentinel
from .telemetry import JsonlSink, Telemetry
from .trace import ProfilerWindow, TraceWriter

__all__ = [
    "Telemetry", "JsonlSink", "TraceWriter", "ProfilerWindow",
    "RecompileSentinel", "RecompileError", "MemoryWatermark",
    "analytic_state_bytes", "device_memory_stats",
]
