"""Measured-vs-analytic reconciliation: profile buckets against roofline floors.

Joins a :mod:`monitor.profile_ingest` per-step wall decomposition (what
the device *actually* spent its time on) against the
:mod:`monitor.cost_model` analytic per-path floors (what perfect
execution *should* cost) and answers three questions:

1. **How far over the floor is each component?** Per component
   ``measured_over_floor`` ratio: measured compute-side busy time
   (gemm + pallas + unattributed device work) vs the fused per-step
   ``max(t_compute, t_hbm)`` floor; ``collective_ici`` wall vs the
   summed ``t_comm`` floor; ``collective_dcn`` wall vs ``t_dcn``.
2. **Did the predicted bound come true?** The cost model predicts a
   binding ceiling per step (``BOUND_COMPUTE``/``HBM``/``INTERCONNECT``
   /``DCN``); the dominant measured bucket either confirms it
   (``verdict: "match"``) or contradicts it (``"mismatch"`` — the
   interesting case: e.g. predicted compute-bound but the wire or the
   host dominates the wall).
3. **Where should a human look?** ``divergences`` lists every component
   whose measured wall exceeds its floor by more than the configurable
   ``threshold`` (ratio for floored components; for zero-floor
   components like ``host``, a fraction of the per-step wall) — each one
   becomes a structured ``reconcile_divergence`` telemetry event.

Pure host-side arithmetic over already-computed dicts — no jax, no
device work; runs at the telemetry report boundary.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .cost_model import (BOUND_COMPUTE, BOUND_DCN, BOUND_HBM,
                         BOUND_INTERCONNECT)

__all__ = ["reconcile", "divergence_events",
           "DEFAULT_THRESHOLD", "DEFAULT_HOST_FRAC"]

# A component this far over its analytic floor is flagged. 3x is lax on
# purpose: CPU meshes and tiny models sit far off the roofline; the knob
# (telemetry.profile.divergence_threshold) tightens it on hardware.
DEFAULT_THRESHOLD = 3.0
# Zero-floor components (host transfers/stalls have no analytic floor —
# ideally they don't exist) diverge past this fraction of the step wall.
DEFAULT_HOST_FRAC = 0.10

# Which measured bucket confirms which predicted bound. gemm/pallas busy
# time realizes both the compute and the HBM ceiling (a fused kernel is
# simultaneously doing flops and streaming bytes — the trace cannot
# split them); idle confirms nothing.
_BUCKET_CONFIRMS = {
    "gemm": (BOUND_COMPUTE, BOUND_HBM),
    "pallas": (BOUND_COMPUTE, BOUND_HBM),
    "unattributed": (BOUND_COMPUTE, BOUND_HBM),
    "collective_ici": (BOUND_INTERCONNECT,),
    "collective_dcn": (BOUND_DCN,),
}


def _ratio(measured: float, floor: float) -> Optional[float]:
    if floor <= 1e-9:
        return None
    return round(measured / floor, 4)


def _step_floors(cost_model: Dict[str, Any]) -> Dict[str, float]:
    """Fused per-step component floors (ms) from the cost-model payload:
    sum over the step's paths, weighted by invocations/step. The
    compute-side floor takes ``max(t_compute, t_hbm)`` per path (they
    overlap inside one program), then adds across paths (distinct XLA
    programs cannot overlap)."""
    step = cost_model.get("step") or {}
    paths = cost_model.get("paths") or {}
    floors = {"compute": 0.0, "collective_ici": 0.0, "collective_dcn": 0.0}
    for name, weight in (step.get("paths") or {}).items():
        p = paths.get(name)
        if not p or not p.get("available"):
            continue
        w = float(weight)
        floors["compute"] += max(p.get("t_compute_ms", 0.0),
                                 p.get("t_hbm_ms", 0.0)) * w
        floors["collective_ici"] += p.get("t_comm_ms", 0.0) * w
        floors["collective_dcn"] += p.get("t_dcn_ms", 0.0) * w
    return {k: round(v, 6) for k, v in floors.items()}


def reconcile(decomposition: Dict[str, Any],
              cost_model: Dict[str, Any],
              threshold: float = DEFAULT_THRESHOLD,
              host_frac: float = DEFAULT_HOST_FRAC) -> Dict[str, Any]:
    """Join one ingest decomposition against one cost-model payload.

    ``decomposition`` is :func:`profile_ingest.ingest`'s summary (needs
    ``per_step_ms`` + ``per_step_wall_ms``); ``cost_model`` is
    :func:`cost_model.build_cost_model`'s payload. Returns the
    JSONL-ready reconciliation record; feed it to
    :func:`divergence_events` for the telemetry event list.
    """
    per_step = decomposition.get("per_step_ms") or {}
    wall_ms = float(decomposition.get("per_step_wall_ms", 0.0) or 0.0)
    floors = _step_floors(cost_model)

    compute_busy = (per_step.get("gemm", 0.0) + per_step.get("pallas", 0.0)
                    + per_step.get("unattributed", 0.0))
    components: Dict[str, Dict[str, Any]] = {
        "compute": {
            "measured_ms": round(compute_busy, 6),
            "floor_ms": floors["compute"],
            "measured_over_floor": _ratio(compute_busy, floors["compute"]),
        },
        "collective_ici": {
            "measured_ms": round(per_step.get("collective_ici", 0.0), 6),
            "floor_ms": floors["collective_ici"],
            "measured_over_floor": _ratio(
                per_step.get("collective_ici", 0.0),
                floors["collective_ici"]),
        },
        "collective_dcn": {
            "measured_ms": round(per_step.get("collective_dcn", 0.0), 6),
            "floor_ms": floors["collective_dcn"],
            "measured_over_floor": _ratio(
                per_step.get("collective_dcn", 0.0),
                floors["collective_dcn"]),
        },
        "host": {
            "measured_ms": round(per_step.get("host", 0.0), 6),
            "floor_ms": 0.0,
            "wall_frac": round(per_step.get("host", 0.0) / wall_ms, 4)
            if wall_ms > 0 else None,
        },
    }

    # Divergences: floored components by ratio; host by wall fraction.
    divergences: List[Dict[str, Any]] = []
    for comp in ("compute", "collective_ici", "collective_dcn"):
        c = components[comp]
        r = c["measured_over_floor"]
        c["diverged"] = bool(r is not None and r > threshold)
        if c["diverged"]:
            divergences.append({
                "component": comp, "measured_ms": c["measured_ms"],
                "floor_ms": c["floor_ms"], "measured_over_floor": r,
                "threshold": threshold})
    host = components["host"]
    hf = host["wall_frac"]
    host["diverged"] = bool(hf is not None and hf > host_frac)
    if host["diverged"]:
        divergences.append({
            "component": "host", "measured_ms": host["measured_ms"],
            "floor_ms": 0.0, "wall_frac": hf, "threshold": host_frac})

    # Boundedness verdict: dominant measured bucket vs predicted bound.
    busy = {b: per_step.get(b, 0.0) for b in _BUCKET_CONFIRMS}
    dominant = max(busy, key=busy.get) if any(v > 0 for v in busy.values()) \
        else None
    predicted = (cost_model.get("step") or {}).get("bound")
    if dominant is None or predicted is None:
        verdict = "indeterminate"
    elif predicted in _BUCKET_CONFIRMS[dominant]:
        verdict = "match"
    else:
        verdict = "mismatch"

    # Per-path boundedness: every registered path gets a verdict — does
    # the step-level measured dominant bucket confirm the path's own
    # predicted bound? (Buckets are step-scoped; per-path device
    # attribution needs hardware annotations we don't require.)
    path_verdicts: Dict[str, Dict[str, Any]] = {}
    for name, p in (cost_model.get("paths") or {}).items():
        if not p.get("available"):
            path_verdicts[name] = {"bound": None, "floor_ms": None,
                                   "verdict": "unavailable"}
            continue
        pb = p.get("bound")
        if dominant is None or pb is None:
            pv = "indeterminate"
        elif pb in _BUCKET_CONFIRMS[dominant]:
            pv = "match"
        else:
            pv = "mismatch"
        path_verdicts[name] = {
            "bound": pb, "floor_ms": round(p.get("floor_ms", 0.0), 6),
            "verdict": pv}

    return {
        "per_step_wall_ms": round(wall_ms, 6),
        "threshold": threshold,
        "host_frac_threshold": host_frac,
        "components": components,
        "dominant_bucket": dominant,
        "predicted_bound": predicted,
        "verdict": verdict,
        "paths": path_verdicts,
        "divergences": divergences,
    }


def divergence_events(reconciliation: Dict[str, Any]
                      ) -> List[Dict[str, Any]]:
    """Payloads for the ``reconcile_divergence`` telemetry events — one
    per diverged component, self-describing (component, measured, floor,
    the threshold that tripped)."""
    return [dict(d, event="reconcile_divergence")
            for d in reconciliation.get("divergences", [])]
