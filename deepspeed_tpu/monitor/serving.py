"""Per-request goodput metrics for the serving tier.

The serving analogues of the training spine's step records: while the
trainer's unit of accounting is the optimizer step, serving accounts per
REQUEST (TTFT — time to first token, queue wait included; TPOT — mean
time per output token after the first) and per decode ITERATION (batch
occupancy = active slots / total slots; the number that says whether
continuous batching is actually keeping the chip busy).

All inputs are host wall-clock and host counters — aggregation adds
zero device syncs. ``ServingAggregator.snapshot()`` is the one shape
every consumer speaks: the engine's drain extra, SERVE_BENCH.json, and
``tools/telemetry_report.py``'s ``serving`` section.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (the same rule
    tools/telemetry_report.py uses — keep the figures comparable)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def _pcts(vals: List[float]) -> Dict[str, float]:
    s = sorted(vals)
    return {"p50": round(percentile(s, 50), 3),
            "p95": round(percentile(s, 95), 3),
            "mean": round(sum(s) / len(s), 3) if s else 0.0,
            "n": len(s)}


class ServingAggregator:
    """Accumulates per-iteration and per-request serving metrics.

    ``label`` names the replica this aggregator feeds (the multi-
    replica router runs one engine — and one aggregator — per replica);
    snapshots carry it so downstream consumers (telemetry_report,
    SERVE_BENCH.json) never interleave two replicas' percentile streams
    into one misleading distribution. ``ServingAggregator.merged``
    builds the honest aggregate view by POOLING the raw samples.
    """

    def __init__(self, max_slots: int, label: Optional[str] = None):
        self.max_slots = max(1, int(max_slots))
        self.label = label
        self.t0 = time.perf_counter()
        self.iterations = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.completed = 0
        # Paged-cache accounting (engine-fed; stays empty — and out of
        # the snapshot — on slot-major engines that predate it).
        self.prompt_tokens_admitted = 0
        self.cached_tokens_admitted = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Analytic attend-work accounting (engine-fed, paged engines
        # only): the same iterations priced BOTH ways — the Pallas
        # kernel's live-context term vs the one-hot contraction's
        # pool-capacity term. ``attend_mode`` names which one actually
        # ran; the totals are host arithmetic (projections), never
        # device measurements.
        self.attend_mode: Optional[str] = None
        self.attend_flops_kernel = 0
        self.attend_flops_onehot = 0
        self.attend_bytes_kernel = 0
        self.attend_bytes_onehot = 0
        self.attend_tokens = 0
        # Admission-rejection accounting (the reservation gate's retries
        # used to be invisible): total rejected reservations plus the
        # per-completed-request attempt counts.
        self.reservations_rejected = 0
        self._admission_attempts: List[float] = []
        # Optional overlays (engine-attached): a ServingGoodputLedger
        # and an SLOTracker (monitor/serving_slo.py) — or, on a merged
        # aggregator, their already-settled snapshot dicts. When unset
        # the snapshot omits the sections (skip-never-fail downstream).
        self.ledger: Optional[Any] = None
        self.slo: Optional[Any] = None
        self._occupancy: List[float] = []
        self._decode_ms: List[float] = []
        self._ttft_ms: List[float] = []
        self._tpot_ms: List[float] = []
        self._queue_wait_ms: List[float] = []
        self._service_ttft_ms: List[float] = []
        self._hbm_per_token: List[float] = []
        self._cache_bytes: List[int] = []

    # ---- per decode iteration ---- #
    def note_iteration(self, active_slots: int, decode_s: float,
                       cache_bytes: Optional[int] = None,
                       context_tokens: Optional[int] = None,
                       emitted_tokens: Optional[int] = None) -> None:
        """``emitted_tokens`` defaults to one per active slot (plain
        decode); the speculative verify step passes the real count.
        ``cache_bytes`` / ``context_tokens`` sample the HBM the cache
        holds against the tokens it serves — the hbm_bytes_per_token
        series the paging win is measured on."""
        self.iterations += 1
        self.decode_tokens += int(emitted_tokens
                                  if emitted_tokens is not None
                                  else active_slots)
        self._occupancy.append(active_slots / self.max_slots)
        self._decode_ms.append(decode_s * 1e3)
        if cache_bytes is not None and context_tokens:
            self._cache_bytes.append(int(cache_bytes))
            self._hbm_per_token.append(cache_bytes / context_tokens)

    def note_prefill(self, prompt_tokens: int) -> None:
        self.prefill_tokens += int(prompt_tokens)

    def note_admit(self, prompt_tokens: int, cached_tokens: int) -> None:
        """Prefix-cache accounting at admission: how many of the
        prompt's tokens rode already-resident blocks."""
        self.prompt_tokens_admitted += int(prompt_tokens)
        self.cached_tokens_admitted += int(cached_tokens)

    def note_spec(self, proposed: int, accepted: int) -> None:
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)

    def note_attend(self, flops_kernel: int, flops_onehot: int,
                    bytes_kernel: int, bytes_onehot: int,
                    tokens: int) -> None:
        """One iteration's analytic attend work, both ways (see
        InferenceEngine._attend_work); ``tokens`` are the iteration's
        emitted tokens — the per-token denominators."""
        self.attend_flops_kernel += int(flops_kernel)
        self.attend_flops_onehot += int(flops_onehot)
        self.attend_bytes_kernel += int(bytes_kernel)
        self.attend_bytes_onehot += int(bytes_onehot)
        self.attend_tokens += int(tokens)

    def note_reject(self) -> None:
        """One reservation-gate / slot-pool admission rejection."""
        self.reservations_rejected += 1

    # ---- per completed request ---- #
    def note_request(self, ttft_s: float, tpot_s: Optional[float],
                     new_tokens: int,
                     queue_wait_s: Optional[float] = None,
                     service_ttft_s: Optional[float] = None,
                     admission_attempts: Optional[int] = None) -> None:
        """``queue_wait_s``/``service_ttft_s`` split the end-to-end TTFT
        at the admission instant (router backlog vs slow prefill —
        indistinguishable in the pooled ttft figure alone)."""
        self.completed += 1
        self._ttft_ms.append(ttft_s * 1e3)
        if tpot_s is not None:
            self._tpot_ms.append(tpot_s * 1e3)
        if queue_wait_s is not None:
            self._queue_wait_ms.append(queue_wait_s * 1e3)
        if service_ttft_s is not None:
            self._service_ttft_ms.append(service_ttft_s * 1e3)
        if admission_attempts is not None:
            self._admission_attempts.append(float(admission_attempts))

    @property
    def occupancy_mean(self) -> float:
        if not self._occupancy:
            return 0.0
        return sum(self._occupancy) / len(self._occupancy)

    def snapshot(self, wall_s: Optional[float] = None) -> Dict[str, Any]:
        """The canonical serving summary. ``tokens_per_s`` counts
        GENERATED (decode) tokens over the serve wall — prefill tokens
        are reported separately, not inflated into throughput. Fields
        the engine never fed (no paged cache, no spec decode) are
        omitted so pre-paging consumers and the bench gate's
        skip-never-fail rule keep working."""
        wall = wall_s if wall_s is not None \
            else time.perf_counter() - self.t0
        snap = {
            "iterations": self.iterations,
            "completed": self.completed,
            "occupancy_mean": round(self.occupancy_mean, 4),
            "occupancy_p50": round(
                percentile(sorted(self._occupancy), 50), 4),
            "decode_tokens": self.decode_tokens,
            "prefill_tokens": self.prefill_tokens,
            "tokens_per_s": round(self.decode_tokens / wall, 3)
            if wall > 0 else 0.0,
            "wall_s": round(wall, 6),
            "ttft_ms": _pcts(self._ttft_ms),
            "tpot_ms": _pcts(self._tpot_ms),
            "decode_step_ms": _pcts(self._decode_ms),
        }
        if self.label is not None:
            snap["replica"] = self.label
        if self._queue_wait_ms:
            snap["queue_wait_ms"] = _pcts(self._queue_wait_ms)
        if self._service_ttft_ms:
            snap["service_ttft_ms"] = _pcts(self._service_ttft_ms)
        if self.reservations_rejected or self._admission_attempts:
            snap["admission"] = {
                "reservations_rejected": self.reservations_rejected,
                "attempts": _pcts(self._admission_attempts),
            }
        if self.ledger is not None:
            snap["ledger"] = self.ledger.snapshot(wall_s=wall) \
                if hasattr(self.ledger, "snapshot") else self.ledger
        if self.slo is not None:
            slo = self.slo.snapshot() if hasattr(self.slo, "snapshot") \
                else self.slo
            if slo is not None:
                snap["slo"] = slo
        if self._hbm_per_token:
            snap["hbm_bytes_per_token"] = _pcts(self._hbm_per_token)
            snap["cache_bytes_p95"] = int(percentile(
                sorted(self._cache_bytes), 95))
        if self.prompt_tokens_admitted:
            snap["prefix"] = {
                "prompt_tokens": self.prompt_tokens_admitted,
                "cached_tokens": self.cached_tokens_admitted,
                "hit_rate": round(self.cached_tokens_admitted /
                                  self.prompt_tokens_admitted, 4),
            }
        if self.spec_proposed:
            snap["spec"] = {
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "acceptance_rate": round(self.spec_accepted /
                                         self.spec_proposed, 4),
            }
        if self.attend_tokens:
            t = self.attend_tokens
            snap["attend"] = {
                "mode": self.attend_mode or "onehot",
                "flops_per_token": {
                    "kernel": round(self.attend_flops_kernel / t, 1),
                    "onehot": round(self.attend_flops_onehot / t, 1)},
                "hbm_bytes_per_token": {
                    "kernel": round(self.attend_bytes_kernel / t, 1),
                    "onehot": round(self.attend_bytes_onehot / t, 1)},
                "projection": "analytic (host-priced, not a device "
                              "measurement)",
            }
            if self.attend_bytes_kernel:
                # The structural headline: one-hot HBM traffic over the
                # kernel's, same iterations — >1 means the pool
                # outweighs the live contexts it served.
                snap["attend_work_ratio"] = round(
                    self.attend_bytes_onehot / self.attend_bytes_kernel,
                    4)
        return snap

    @classmethod
    def merged(cls, aggs: List["ServingAggregator"],
               label: str = "aggregate") -> "ServingAggregator":
        """The honest aggregate over replicas: raw samples POOLED, not
        percentiles-of-percentiles, counters summed, capacity summed."""
        out = cls(sum(a.max_slots for a in aggs) or 1, label=label)
        for a in aggs:
            out.iterations += a.iterations
            out.decode_tokens += a.decode_tokens
            out.prefill_tokens += a.prefill_tokens
            out.completed += a.completed
            out.prompt_tokens_admitted += a.prompt_tokens_admitted
            out.cached_tokens_admitted += a.cached_tokens_admitted
            out.spec_proposed += a.spec_proposed
            out.spec_accepted += a.spec_accepted
            out.attend_flops_kernel += a.attend_flops_kernel
            out.attend_flops_onehot += a.attend_flops_onehot
            out.attend_bytes_kernel += a.attend_bytes_kernel
            out.attend_bytes_onehot += a.attend_bytes_onehot
            out.attend_tokens += a.attend_tokens
            if out.attend_mode is None:
                out.attend_mode = a.attend_mode
            # Occupancy normalizes per-replica (active/its own slots):
            # pooling the normalized samples keeps the mean meaningful
            # as "fraction of owned capacity busy".
            out._occupancy.extend(a._occupancy)
            out._decode_ms.extend(a._decode_ms)
            out._ttft_ms.extend(a._ttft_ms)
            out._tpot_ms.extend(a._tpot_ms)
            out._queue_wait_ms.extend(a._queue_wait_ms)
            out._service_ttft_ms.extend(a._service_ttft_ms)
            out._admission_attempts.extend(a._admission_attempts)
            out.reservations_rejected += a.reservations_rejected
            out._hbm_per_token.extend(a._hbm_per_token)
            out._cache_bytes.extend(a._cache_bytes)
        # Fleet-level SLO/ledger views: pooled outcomes and bucket-wise
        # sums, stored as settled dicts (a merged aggregator keeps
        # accumulating nothing).
        from .serving_slo import ServingGoodputLedger, SLOTracker
        trackers = [a.slo for a in aggs if isinstance(a.slo, SLOTracker)]
        if trackers:
            out.slo = SLOTracker.merged(trackers)
        led = [a.ledger.snapshot() for a in aggs
               if a.ledger is not None and hasattr(a.ledger, "snapshot")]
        led += [a.ledger for a in aggs if isinstance(a.ledger, dict)]
        if led:
            out.ledger = ServingGoodputLedger.merged(led)
        return out


__all__ = ["ServingAggregator", "percentile"]
