"""Per-request goodput metrics for the serving tier.

The serving analogues of the training spine's step records: while the
trainer's unit of accounting is the optimizer step, serving accounts per
REQUEST (TTFT — time to first token, queue wait included; TPOT — mean
time per output token after the first) and per decode ITERATION (batch
occupancy = active slots / total slots; the number that says whether
continuous batching is actually keeping the chip busy).

All inputs are host wall-clock and host counters — aggregation adds
zero device syncs. ``ServingAggregator.snapshot()`` is the one shape
every consumer speaks: the engine's drain extra, SERVE_BENCH.json, and
``tools/telemetry_report.py``'s ``serving`` section.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (the same rule
    tools/telemetry_report.py uses — keep the figures comparable)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def _pcts(vals: List[float]) -> Dict[str, float]:
    s = sorted(vals)
    return {"p50": round(percentile(s, 50), 3),
            "p95": round(percentile(s, 95), 3),
            "mean": round(sum(s) / len(s), 3) if s else 0.0,
            "n": len(s)}


class ServingAggregator:
    """Accumulates per-iteration and per-request serving metrics."""

    def __init__(self, max_slots: int):
        self.max_slots = max(1, int(max_slots))
        self.t0 = time.perf_counter()
        self.iterations = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.completed = 0
        self._occupancy: List[float] = []
        self._decode_ms: List[float] = []
        self._ttft_ms: List[float] = []
        self._tpot_ms: List[float] = []

    # ---- per decode iteration ---- #
    def note_iteration(self, active_slots: int, decode_s: float) -> None:
        self.iterations += 1
        self.decode_tokens += int(active_slots)
        self._occupancy.append(active_slots / self.max_slots)
        self._decode_ms.append(decode_s * 1e3)

    def note_prefill(self, prompt_tokens: int) -> None:
        self.prefill_tokens += int(prompt_tokens)

    # ---- per completed request ---- #
    def note_request(self, ttft_s: float, tpot_s: Optional[float],
                     new_tokens: int) -> None:
        self.completed += 1
        self._ttft_ms.append(ttft_s * 1e3)
        if tpot_s is not None:
            self._tpot_ms.append(tpot_s * 1e3)

    @property
    def occupancy_mean(self) -> float:
        if not self._occupancy:
            return 0.0
        return sum(self._occupancy) / len(self._occupancy)

    def snapshot(self, wall_s: Optional[float] = None) -> Dict[str, Any]:
        """The canonical serving summary. ``tokens_per_s`` counts
        GENERATED (decode) tokens over the serve wall — prefill tokens
        are reported separately, not inflated into throughput."""
        wall = wall_s if wall_s is not None \
            else time.perf_counter() - self.t0
        return {
            "iterations": self.iterations,
            "completed": self.completed,
            "occupancy_mean": round(self.occupancy_mean, 4),
            "occupancy_p50": round(
                percentile(sorted(self._occupancy), 50), 4),
            "decode_tokens": self.decode_tokens,
            "prefill_tokens": self.prefill_tokens,
            "tokens_per_s": round(self.decode_tokens / wall, 3)
            if wall > 0 else 0.0,
            "wall_s": round(wall, 6),
            "ttft_ms": _pcts(self._ttft_ms),
            "tpot_ms": _pcts(self._tpot_ms),
            "decode_step_ms": _pcts(self._decode_ms),
        }


__all__ = ["ServingAggregator", "percentile"]
