"""deepspeed_tpu — a TPU-native large-scale training framework.

Capability parity with DeepSpeed v0.3.11 (reference: `/root/reference`),
re-designed for JAX/XLA/Pallas on TPU: SPMD over a named device mesh instead
of per-process NCCL collectives, bf16-first precision, jit-compiled train
steps, Pallas kernels for the fused ops.

Public surface parity with the reference ``deepspeed/__init__.py``:
``initialize()``, ``add_config_arguments()``, ``init_distributed()``, plus
the pipeline module, ops, and checkpointing re-exports.
"""
from .version import __version__

from .runtime.config import DeepSpeedConfig
from .runtime import lr_schedules
from .utils.logging import logger, log_dist


def initialize(args=None, model=None, optimizer=None, model_params=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None, rng=None, param_shardings=None, mesh=None,
               zero3_scan=None):
    """Initialize the engine. Parity with reference ``__init__.py:50``.

    Returns a tuple of ``(engine, optimizer, dataloader, lr_scheduler)``.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.pipe.module import PipelineModule
    from .runtime.pipe.engine import PipelineEngine

    cfg = config if config is not None else config_params
    if cfg is None and args is not None:
        cfg = getattr(args, "deepspeed_config", None)
    if cfg is None:
        raise ValueError("DeepSpeed requires a config via `config=`, "
                         "`config_params=`, or args.deepspeed_config")

    from .models.gpt2_pipe import PipeSpec
    if isinstance(model, (PipelineModule, PipeSpec)):
        pipe_mpu = mpu
        if pipe_mpu is None and isinstance(model, PipelineModule):
            pipe_mpu = model.mpu()
        engine = PipelineEngine(args=args, model=model, optimizer=optimizer,
                                model_params=model_params, training_data=training_data,
                                lr_scheduler=lr_scheduler, mpu=pipe_mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn, config=cfg, rng=rng,
                                mesh=mesh)
    else:
        engine = DeepSpeedEngine(args=args, model=model, optimizer=optimizer,
                                 model_params=model_params, training_data=training_data,
                                 lr_scheduler=lr_scheduler, mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn, config=cfg, rng=rng,
                                 param_shardings=param_shardings, mesh=mesh,
                                 zero3_scan=zero3_scan)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI flags (reference __init__.py:193)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user scripts).")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; deprecated on TPU (topology is discovered).")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias for --deepspeed.")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias for --deepspeed_config.")
    return parser


def init_distributed(dist_backend: str = "xla", auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500, verbose: bool = True,
                     timeout=None, init_method=None):
    """Initialize the multi-host runtime (reference utils/distributed.py:12).

    On TPU this wraps ``jax.distributed.initialize`` using environment
    variables set by the launcher; a no-op for single-process runs.
    """
    from .parallel.comm import init_distributed as _init
    return _init(dist_backend=dist_backend, distributed_port=distributed_port,
                 verbose=verbose, init_method=init_method)
