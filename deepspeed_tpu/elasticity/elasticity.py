"""Batch-size elasticity calculator.

Parity with reference ``elasticity/elasticity.py``: from a set of candidate
micro-batch sizes, an upper bound on the global batch, and device-count
bounds, find the global batch size whose set of compatible device counts is
maximal (candidate enumeration elasticity.py:61-121; scoring prefers more
device counts, then larger batch, elasticity.py:94-121; public entry
``compute_elastic_config`` elasticity.py:240-332). Pure math — identical
algorithm applies on TPU, where "gpus" reads as data-parallel chip count.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple, Union

from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)
from .. import constants as C
from ..utils.logging import logger

# Highly composite numbers: each has more divisors than any smaller positive
# integer, so batch = micro * HCN maximizes the count of compatible device
# counts. Same table the reference uses.
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400,
]


def get_candidate_batch_sizes(base_list: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """Largest batch ≤ max for each micro-batch base, scaled by an HCN."""
    candidate_batch_size = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidate_batch_size.add(base)
            continue
        limit = max_acceptable_batch_size // base
        best = 1
        for hcn in HCN_LIST:
            if hcn > limit:
                break
            best = hcn
        candidate_batch_size.add(best * base)
    return sorted(candidate_batch_size)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """All device counts g with some micro m s.t. g divides batch/m."""
    valid_gpus = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_gpus = batch_size // micro_batch
        if min_valid_gpus <= max_gpus <= max_valid_gpus:
            valid_gpus.add(max_gpus)
        for i in range(1, max_gpus // 2 + 1):
            if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                valid_gpus.add(i)
    return sorted(valid_gpus)


def get_best_candidates(candidate_batch_sizes: List[int], micro_batches: List[int],
                        min_gpus: int, max_gpus: int,
                        prefer_larger: bool = True) -> Tuple[int, List[int]]:
    max_valid_gpus = 0
    valid_gpus: List[int] = []
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        better_count = len(current_valid_gpus) > max_valid_gpus
        tie = len(current_valid_gpus) == max_valid_gpus
        prefer = prefer_larger and batch_size > final_batch_size
        if current_valid_gpus and (better_count or (tie and prefer)):
            max_valid_gpus = len(current_valid_gpus)
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches: List[int], max_acceptable_batch_size: int,
                             min_gpus: int, max_gpus: int,
                             prefer_larger: bool = True) -> Tuple[int, List[int]]:
    candidates = get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def elasticity_enabled(ds_config: Dict[str, Any]) -> bool:
    if C.ELASTICITY not in ds_config:
        return False
    return ds_config[C.ELASTICITY].get(C.ENABLED, C.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict[str, Any]) -> None:
    """Verify the elastic config hasn't changed vs. the scheduler-stamped hash.

    Parity with elasticity.py:207-239: the scheduler records
    DEEPSPEED_ELASTICITY_CONFIG; a run under it must use the same config.
    """
    import os
    env_key = "DEEPSPEED_ELASTICITY_CONFIG"
    if env_key in os.environ:
        scheduler_dict = json.loads(os.environ[env_key])
        scheduler_hash = hashlib.sha1(
            json.dumps(scheduler_dict, sort_keys=True).encode()).hexdigest()
        runtime_hash = hashlib.sha1(
            json.dumps(runtime_elastic_config_dict, sort_keys=True).encode()).hexdigest()
        if scheduler_hash != runtime_hash:
            raise ElasticityConfigError(
                "Elastic config changed between scheduler and runtime: "
                f"{scheduler_dict} != {runtime_elastic_config_dict}")


def compute_elastic_config(ds_config: Union[str, Dict[str, Any]],
                           target_deepspeed_version: str,
                           world_size: int = 0) -> Tuple[int, List[int], Optional[int]]:
    """Main entry (elasticity.py:240-332).

    Returns (final_batch_size, valid_gpus, micro_batch_size-for-world_size).
    When ``world_size`` is 0 the micro batch is None (config-time query).
    """
    if isinstance(ds_config, str):
        ds_config = json.loads(ds_config)
    if not elasticity_enabled(ds_config):
        raise ElasticityError("Elasticity is not enabled in the given ds_config")

    elastic_config = ElasticityConfig(ds_config[C.ELASTICITY])
    if float(elastic_config.version) > C.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {elastic_config.version}, latest is "
            f"{C.LATEST_ELASTICITY_VERSION}")
    ensure_immutable_elastic_config(ds_config[C.ELASTICITY])

    final_batch_size, valid_gpus = _get_compatible_gpus_v01(
        micro_batches=elastic_config.micro_batches,
        max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
        min_gpus=elastic_config.min_gpus,
        max_gpus=elastic_config.max_gpus,
        prefer_larger=elastic_config.prefer_larger_batch_size)
    final_batch_size = int(final_batch_size)

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current list of "
                f"valid device counts: {valid_gpus}")
        # Largest compatible micro batch for this world size.
        micro_batch_size = None
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        if micro_batch_size is None:
            raise ElasticityError(
                f"No compatible micro batch for world size {world_size} and final "
                f"batch {final_batch_size} from {elastic_config.micro_batches}")
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus, None
