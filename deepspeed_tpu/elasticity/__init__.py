from .elasticity import (compute_elastic_config, elasticity_enabled,
                         get_candidate_batch_sizes, get_valid_gpus,
                         get_best_candidates, HCN_LIST)
from .config import (ElasticityConfig, ElasticityError, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)
