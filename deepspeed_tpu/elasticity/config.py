"""Elasticity config.

Parity with reference ``elasticity/config.py``: fields enabled,
max_train_batch_size, micro_batch_sizes, min/max_gpus, min_time, version,
prefer_larger_batch, ignore_non_elastic_batch_info.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Union

from .. import constants as C


class ElasticityError(Exception):
    """Base elasticity error."""


class ElasticityConfigError(ElasticityError):
    """Invalid elasticity config."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size is not valid for the elastic config."""


class ElasticityConfig:
    """Controls batch-size elasticity.

    Example::

        "elasticity": {
            "enabled": true,
            "max_train_batch_size": 2000,
            "micro_batch_sizes": [2, 4, 6],
            "min_gpus": 1,
            "max_gpus": 10000,
            "min_time": 20,
            "version": 0.1
        }
    """

    def __init__(self, param_dict: Union[Dict[str, Any], str]):
        if isinstance(param_dict, str):
            param_dict = json.loads(param_dict)
        self.enabled = param_dict.get(C.ENABLED, C.ENABLED_DEFAULT)
        # Required keys: a typo'd key must fail loudly, not silently train
        # with default batch sizes (reference elasticity/config.py behavior).
        if C.MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
            raise ElasticityConfigError(
                f"Elasticity config missing required key '{C.MAX_ACCEPTABLE_BATCH_SIZE}'")
        if C.MICRO_BATCHES not in param_dict:
            raise ElasticityConfigError(
                f"Elasticity config missing required key '{C.MICRO_BATCHES}'")
        self.max_acceptable_batch_size = param_dict[C.MAX_ACCEPTABLE_BATCH_SIZE]
        self.micro_batches = param_dict[C.MICRO_BATCHES]
        if not isinstance(self.micro_batches, list) or len(self.micro_batches) == 0:
            raise ElasticityConfigError(
                f"'{C.MICRO_BATCHES}' must be a non-empty list, got {self.micro_batches}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"'{C.MICRO_BATCHES}' must contain positive ints, got {self.micro_batches}")
        self.min_gpus = param_dict.get(C.MIN_GPUS, C.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(C.MAX_GPUS, C.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"Invalid gpu bounds: min_gpus={self.min_gpus}, max_gpus={self.max_gpus}")
        self.min_time = param_dict.get(C.MIN_TIME, C.MIN_TIME_DEFAULT)
        self.version = param_dict.get(C.VERSION, C.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            C.PREFER_LARGER_BATCH, C.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            C.IGNORE_NON_ELASTIC_BATCH_INFO, C.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)
