from .runner import fetch_hostfile, parse_resource_filter, main  # noqa: F401
