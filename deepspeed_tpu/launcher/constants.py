"""Launcher constants (parity: reference launcher/constants.py)."""

PDSH_LAUNCHER = "pdsh"
SSH_LAUNCHER = "ssh"
GCLOUD_LAUNCHER = "gcloud"

DEFAULT_HOSTFILE = "/job/hostfile"
DEFAULT_COORDINATOR_PORT = 29500

# Env vars forwarded to remote processes when present locally (the TPU
# analogue of the reference's NCCL/PYTHON/MV2/UCX prefix list).
EXPORT_ENV_PREFIXES = ["TPU", "JAX", "XLA", "LIBTPU", "PYTHON", "DS_"]

# A `.deepspeed_env` file in ~ or . adds KEY=VALUE exports for all nodes
# (reference runner.py:27-28).
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def pod_index_of(host: str):
    """Trailing integer of a hostname ('worker-3' -> 3), or None.

    The single source of truth for mapping world-info hostnames to Cloud
    TPU pod worker indices — used by BOTH the gcloud dispatcher (which
    picks --worker=... indices) and launch._infer_node_rank (which maps a
    worker's TPU_WORKER_ID back to its world-info rank); the two must
    agree or ranks misalign.
    """
    digits = ""
    for ch in reversed(host):
        if ch.isdigit():
            digits = ch + digits
        else:
            break
    return int(digits) if digits else None
