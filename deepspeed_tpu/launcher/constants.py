"""Launcher constants (parity: reference launcher/constants.py)."""

PDSH_LAUNCHER = "pdsh"
SSH_LAUNCHER = "ssh"

DEFAULT_HOSTFILE = "/job/hostfile"
DEFAULT_COORDINATOR_PORT = 29500

# Env vars forwarded to remote processes when present locally (the TPU
# analogue of the reference's NCCL/PYTHON/MV2/UCX prefix list).
EXPORT_ENV_PREFIXES = ["TPU", "JAX", "XLA", "LIBTPU", "PYTHON", "DS_"]

# A `.deepspeed_env` file in ~ or . adds KEY=VALUE exports for all nodes
# (reference runner.py:27-28).
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
