"""Multi-node backends: build the remote command that starts launch.py on
every host (parity: reference launcher/multinode_runner.py:35,78 — PDSH and
a plain-ssh fallback; no MPI runner: JAX's coordinator bootstraps from the
env contract, no mpirun required on TPU pods).
"""
from __future__ import annotations

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def backend_exists(self) -> bool:
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        ...

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def _launch_cmd(self, coordinator: str, node_rank_flag: str) -> List[str]:
        return [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            f"--coordinator_addr={coordinator}",
            f"--coordinator_port={self.args.coordinator_port}",
            f"--procs_per_node={self.args.procs_per_node}",
            node_rank_flag,
            self.user_script,
        ] + self.user_arguments


class PDSHRunner(MultiNodeRunner):
    """Fan out over pdsh; node rank inferred from hostname on each node."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        exports = ""
        for key, val in self.exports.items():
            exports += f"export {key}={shlex.quote(val)}; "
        # -S propagates the largest remote exit code into pdsh's own
        # (without it a dead worker looks like success).
        # node_rank=-1: each node matches its hostname in the world info.
        return [
            "pdsh", "-S", "-f", "1024", "-w", active_workers,
        ] + (self.args.launcher_args.split() if self.args.launcher_args
             else []) + [
            exports + f"cd {os.path.abspath('.')}; " +
            " ".join(self._launch_cmd(coordinator, "--node_rank=-1"))
        ]


class SSHRunner(MultiNodeRunner):
    """Sequential ssh fan-out (no pdsh dependency): one ssh per host, each
    backgrounded by the shell; rank passed explicitly."""

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        exports = ""
        for key, val in self.exports.items():
            exports += f"export {key}={shlex.quote(val)}; "
        cmds = []
        for rank, host in enumerate(active_resources.keys()):
            remote = exports + f"cd {os.path.abspath('.')}; " + \
                " ".join(self._launch_cmd(coordinator, f"--node_rank={rank}"))
            cmds.append(f"ssh {host} {shlex.quote(remote)}")
        # Fan out, wait for each, and exit with a nonzero code if ANY host
        # failed (plain `wait` would always return 0 and mask dead jobs).
        script = (" pids=(); " +
                  " ".join(f"{c} & pids+=($!);" for c in cmds) +
                  " rc=0; for p in \"${pids[@]}\"; do"
                  " wait \"$p\" || rc=$?; done; exit $rc")
        return ["/bin/bash", "-c", script]
