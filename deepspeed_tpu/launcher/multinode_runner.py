"""Multi-node backends: build the remote command that starts launch.py on
every host (parity: reference launcher/multinode_runner.py:35,78 — PDSH and
a plain-ssh fallback; no MPI runner: JAX's coordinator bootstraps from the
env contract, no mpirun required on TPU pods).
"""
from __future__ import annotations

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def backend_exists(self) -> bool:
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        ...

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def _launch_cmd(self, coordinator: str, node_rank_flag: str) -> List[str]:
        return [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            f"--coordinator_addr={coordinator}",
            f"--coordinator_port={self.args.coordinator_port}",
            f"--procs_per_node={self.args.procs_per_node}",
            node_rank_flag,
            self.user_script,
        ] + self.user_arguments

    def _remote_shell_cmd(self, coordinator: str, node_rank_flag: str,
                          skip_exports=()) -> str:
        """The full remote shell line every backend dispatches: exports,
        cd into the launch directory, then launch.py."""
        exports = ""
        for key, val in self.exports.items():
            if key in skip_exports:
                continue
            exports += f"export {key}={shlex.quote(val)}; "
        return exports + f"cd {os.path.abspath('.')}; " + \
            " ".join(self._launch_cmd(coordinator, node_rank_flag))


class PDSHRunner(MultiNodeRunner):
    """Fan out over pdsh; node rank inferred from hostname on each node."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        # -S propagates the largest remote exit code into pdsh's own
        # (without it a dead worker looks like success).
        # node_rank=-1: each node matches its hostname in the world info.
        return [
            "pdsh", "-S", "-f", "1024", "-w", active_workers,
        ] + (self.args.launcher_args.split() if self.args.launcher_args
             else []) + [
            self._remote_shell_cmd(coordinator, "--node_rank=-1")
        ]


class GcloudTPURunner(MultiNodeRunner):
    """Managed Cloud-TPU pod dispatch — the TPU-native analogue of the
    reference's OpenMPI/MVAPICH runners (launcher/multinode_runner.py:
    78,118): instead of mpirun over an IB fabric, one
    ``gcloud compute tpus tpu-vm ssh --worker=all`` fans the identical
    launch command out to every worker of a pod slice; each worker
    resolves its node rank from the Cloud-TPU ``TPU_WORKER_ID`` env (see
    launch._infer_node_rank), the pod analogue of OMPI_COMM_WORLD_RANK.

    Requires ``--tpu_name`` (and usually ``--tpu_zone``); extra gcloud
    flags (``--project=...``) pass through ``--launcher_args``.
    """

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    # Per-worker identity vars must NEVER be forwarded from the
    # controller: each pod worker's own values are its rank/peer source.
    WORKER_IDENTITY_VARS = ("TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES")

    @staticmethod
    def worker_indices(active_resources) -> List[int]:
        """Pod worker index of each active host: a trailing integer in the
        hostname when every host has one (so include/exclude subsets keep
        their true pod indices), else hostfile position."""
        from .constants import pod_index_of
        hosts = list(active_resources.keys())
        tails = [pod_index_of(h) for h in hosts]
        if all(t is not None for t in tails) and len(set(tails)) == len(tails):
            return tails
        return list(range(len(hosts)))

    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        if not getattr(self.args, "tpu_name", None):
            raise ValueError("--launcher=gcloud requires --tpu_name "
                             "(the Cloud TPU pod slice to dispatch onto)")
        remote = self._remote_shell_cmd(
            coordinator, "--node_rank=-1",
            skip_exports=self.WORKER_IDENTITY_VARS)
        # Dispatch ONLY the active workers (never --worker=all: an
        # include/exclude/num_nodes filter would otherwise start excluded
        # workers, which rank themselves out of range and fail the job).
        workers = ",".join(
            str(i) for i in self.worker_indices(active_resources))
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
               self.args.tpu_name, f"--worker={workers}",
               f"--command={remote}"]
        if getattr(self.args, "tpu_zone", None):
            cmd.insert(5, f"--zone={self.args.tpu_zone}")
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        return cmd


class SSHRunner(MultiNodeRunner):
    """Sequential ssh fan-out (no pdsh dependency): one ssh per host, each
    backgrounded by the shell; rank passed explicitly."""

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources, coordinator) -> List[str]:
        cmds = []
        for rank, host in enumerate(active_resources.keys()):
            remote = self._remote_shell_cmd(coordinator,
                                            f"--node_rank={rank}")
            cmds.append(f"ssh {host} {shlex.quote(remote)}")
        # Fan out, wait for each, and exit with a nonzero code if ANY host
        # failed (plain `wait` would always return 0 and mask dead jobs).
        script = (" pids=(); " +
                  " ".join(f"{c} & pids+=($!);" for c in cmds) +
                  " rc=0; for p in \"${pids[@]}\"; do"
                  " wait \"$p\" || rc=$?; done; exit $rc")
        return ["/bin/bash", "-c", script]
