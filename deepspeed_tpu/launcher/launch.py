"""Per-node launcher: spawn and supervise this host's worker processes.

Capability parity with reference ``launcher/launch.py:67`` (world-info
decode, global-rank mapping, env plumbing, subprocess spawn, and
kill-all-on-any-failure supervision via signal handler), with the TPU
process model: ONE worker per host by default (JAX drives every local chip
from a single process), ``--procs_per_node > 1`` for CPU-simulated meshes.

Env contract produced here and consumed by ``parallel/comm.py:37``:
``DS_COORDINATOR_ADDRESS`` (host:port), ``DS_NUM_PROCESSES``,
``DS_PROCESS_ID``, plus ``DS_LOCAL_RANK`` / ``DS_NODE_RANK`` and chip
visibility (``TPU_VISIBLE_CHIPS``) when the hostfile filtered slots.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List

from .runner import decode_world_info
from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="per-node process launcher for deepspeed_tpu")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 {host: [slot,...]} map from the runner")
    parser.add_argument("--node_rank", type=int, default=-1,
                        help="this host's index; defaults to matching "
                             "hostname against world_info keys")
    parser.add_argument("--coordinator_addr", type=str, default="127.0.0.1")
    parser.add_argument("--coordinator_port", type=int, default=29500)
    parser.add_argument("--procs_per_node", type=int, default=1)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def _infer_node_rank(world: dict) -> int:
    import socket
    hostname = socket.gethostname()
    hosts = list(world.keys())
    for cand in (hostname, hostname.split(".")[0], "localhost"):
        if cand in hosts:
            return hosts.index(cand)
    # Managed TPU pod workers (gcloud dispatch): every worker runs the
    # identical command and Cloud TPU exposes its slice index as
    # TPU_WORKER_ID — the pod analogue of mpirun's OMPI_COMM_WORLD_RANK.
    # A filtered launch lists a SUBSET of workers in the world info, so
    # first match the pod index against trailing integers in the host
    # names (worker-1, worker-3, ...), then fall back positionally.
    wid = os.environ.get("TPU_WORKER_ID")
    if wid is not None and wid.isdigit():
        from .constants import pod_index_of
        tails = [pod_index_of(h) for h in hosts]
        # Uniqueness condition must match GcloudTPURunner.worker_indices
        # (multinode_runner.py): with duplicate tails (e.g. 'a-1', 'b-1')
        # the dispatcher falls back to POSITIONAL worker indices, so the
        # worker must rank itself positionally too or ranks misalign.
        if all(t is not None for t in tails) and len(set(tails)) == len(tails):
            # Digit-tailed world: the tails ARE the pod indices; a wid
            # outside them means this worker was filtered out of the
            # launch — positional fallback would duplicate a rank.
            if int(wid) in tails:
                return tails.index(int(wid))
            raise ValueError(
                f"TPU_WORKER_ID={wid} matches no world-info host {hosts}: "
                "this worker is not part of the filtered launch")
        if int(wid) < len(hosts):
            return int(wid)
        raise ValueError(
            f"TPU_WORKER_ID={wid} out of range for world info {hosts}")
    raise ValueError(f"host {hostname} not found in world info {hosts} "
                     "and no usable TPU_WORKER_ID "
                     f"(got {wid!r})")


def _resolve_pod_coordinator(world: dict) -> str:
    """'@pod-coordinator' sentinel: the controller has no route to managed
    pod workers, so the coordinator address is resolved ON each worker
    from Cloud TPU's peer list (TPU_WORKER_HOSTNAMES, comma-separated).
    The coordinator is RANK 0 = the first world-info host; its pod index
    (hostname tail, e.g. 'worker-3' when workers 0-2 were excluded) picks
    the matching peer entry."""
    from .constants import pod_index_of
    peers = [p.strip() for p in
             os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if p.strip()]
    if not peers:
        raise ValueError(
            "coordinator '@pod-coordinator' needs TPU_WORKER_HOSTNAMES on "
            "the worker (standard on Cloud TPU VMs); pass "
            "--coordinator_addr explicitly otherwise")
    first_host = next(iter(world.keys()))
    idx = pod_index_of(first_host)
    if idx is not None and idx < len(peers):
        return peers[idx]
    return peers[0]


def main(args=None) -> int:
    args = parse_args(args)
    world = decode_world_info(args.world_info)
    if args.coordinator_addr == "@pod-coordinator":
        args.coordinator_addr = _resolve_pod_coordinator(world)
    node_rank = args.node_rank if args.node_rank >= 0 else _infer_node_rank(world)
    hosts = list(world.keys())
    assert 0 <= node_rank < len(hosts), \
        f"node_rank {node_rank} out of range for {len(hosts)} hosts"
    ppn = max(1, args.procs_per_node)
    num_processes = len(hosts) * ppn
    slots = world[hosts[node_rank]]

    processes: List[subprocess.Popen] = []

    def sigkill_handler(signum=None, frame=None):
        for p in processes:
            if p.poll() is None:
                logger.info(f"Killing subprocess {p.pid}")
                try:
                    p.kill()
                except Exception:
                    pass
        if signum is not None:
            sys.exit(128 + signum)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    for local_rank in range(ppn):
        env = os.environ.copy()
        process_id = node_rank * ppn + local_rank
        env["DS_COORDINATOR_ADDRESS"] = \
            f"{args.coordinator_addr}:{args.coordinator_port}"
        env["DS_NUM_PROCESSES"] = str(num_processes)
        env["DS_PROCESS_ID"] = str(process_id)
        env["DS_LOCAL_RANK"] = str(local_rank)
        env["DS_NODE_RANK"] = str(node_rank)
        # Chip visibility when the hostfile/include filtered slots
        # (CUDA_VISIBLE_DEVICES analogue, reference launch.py:103-118).
        # Empty slot list (placeholder topology from a hostfile-less
        # gcloud launch) = full visibility: leave the env untouched.
        if slots:
            env["TPU_VISIBLE_CHIPS"] = ",".join(str(s) for s in slots)
            env["DS_LOCAL_SLOT_IDS"] = env["TPU_VISIBLE_CHIPS"]

        cmd = [sys.executable, "-u", args.user_script,
               f"--local_rank={local_rank}"] + args.user_args
        logger.info(f"launching process {process_id}: {' '.join(cmd)}")
        processes.append(subprocess.Popen(cmd, env=env))

    # Supervise: any child failing kills the whole node's processes
    # (reference launch.py:151-167).
    alive = list(processes)
    rc = 0
    try:
        while alive:
            finished = [p for p in alive if p.poll() is not None]
            for p in finished:
                alive.remove(p)
                if p.returncode != 0:
                    logger.error(f"process {p.pid} exited with "
                                 f"code {p.returncode}; killing node")
                    rc = p.returncode
                    sigkill_handler()
                    alive = []
                    break
            time.sleep(0.1)
    finally:
        sigkill_handler()
    return rc


if __name__ == "__main__":
    sys.exit(main())
