"""Multi-host job front-end — the ``deepspeed`` CLI for TPU pods.

Capability parity with reference ``launcher/runner.py:254`` (hostfile
parsing, ``--include/--exclude`` resource filters, coordinator resolution,
world-info encoding, backend dispatch), re-targeted at the TPU process
model: JAX owns every chip on a host from ONE process, so the runner spawns
one worker process per host (times ``--procs_per_node`` for megacore /
CPU-simulation runs), not one per device. Slot filtering maps to chip
visibility (``TPU_VISIBLE_CHIPS``) instead of ``CUDA_VISIBLE_DEVICES``.

Topology sources, in priority order:
1. ``--hostfile`` in MPI style (``worker-0 slots=4``) — reference format;
2. ``--tpu_pod`` : ask the local TPU metadata for pod worker hostnames
   (gated: requires a TPU VM environment);
3. localhost fallback (single host, all local chips).
"""
from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import shutil
import subprocess
import sys
from copy import deepcopy
from typing import Dict, List, Optional

from .constants import (DEEPSPEED_ENVIRONMENT_NAME, DEFAULT_COORDINATOR_PORT,
                        DEFAULT_HOSTFILE, EXPORT_ENV_PREFIXES, GCLOUD_LAUNCHER,
                        PDSH_LAUNCHER,
                        SSH_LAUNCHER)
from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu runner: launch multi-host TPU training")
    parser.add_argument("-H", "--hostfile", type=str, default=DEFAULT_HOSTFILE,
                        help="MPI-style hostfile: '<host> slots=<chips>' per line")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE_SPEC[@NODE_SPEC ...] with "
                             "NODE_SPEC=NAME[:SLOT[,SLOT ...]]")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="same syntax as --include; mutually exclusive")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="use only the first N hosts of the pool")
    parser.add_argument("--num_chips", "--num_gpus", dest="num_chips",
                        type=int, default=-1,
                        help="use chips [0:N) on every host")
    parser.add_argument("--coordinator_port", "--master_port",
                        dest="coordinator_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT)
    parser.add_argument("--coordinator_addr", "--master_addr",
                        dest="coordinator_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        help=f"{PDSH_LAUNCHER} | {SSH_LAUNCHER} | "
                             f"{GCLOUD_LAUNCHER}")
    parser.add_argument("--tpu_name", type=str, default=None,
                        help="Cloud TPU pod slice name (gcloud launcher)")
    parser.add_argument("--tpu_zone", type=str, default=None,
                        help="Cloud TPU zone (gcloud launcher)")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--procs_per_node", type=int, default=1,
                        help="worker processes per host (1 for TPU: JAX owns "
                             "all local chips; >1 for CPU simulation)")
    parser.add_argument("--tpu_pod", action="store_true",
                        help="discover hosts from TPU pod metadata")
    parser.add_argument("--force_multi", action="store_true",
                        help="treat a 1-host pool as multi-node (ssh path)")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional["collections.OrderedDict"]:
    """Parse ``<host> slots=<n>`` lines (reference runner.py:115-142)."""
    if not os.path.isfile(hostfile_path):
        logger.warning("Unable to find hostfile, proceeding with local "
                       "resources only.")
        return None
    resource_pool: "collections.OrderedDict[str, int]" = collections.OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError as err:
                logger.error("Hostfile is not formatted correctly")
                raise err
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def discover_tpu_pod() -> Optional["collections.OrderedDict"]:
    """TPU pod topology from instance metadata (one entry per worker host).

    On Cloud TPU VMs the pod's worker list is exposed via the metadata
    server / ``TPU_WORKER_HOSTNAMES`` env. Gated: returns None when neither
    is available (dev boxes, CI).
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    chips = int(os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "0") or 0)
    if not hostnames:
        return None
    pool: "collections.OrderedDict[str, int]" = collections.OrderedDict()
    for h in hostnames.split(","):
        h = h.strip()
        if h:
            pool[h] = chips if chips > 0 else 4
    return pool


def parse_resource_filter(host_info: Dict[str, List[int]], include_str="",
                          exclude_str="") -> "collections.OrderedDict":
    """Filter ``{host: [slot, ...]}`` by NODE_SPEC strings.

    Same syntax and semantics as reference runner.py:146-231:
    ``worker-0@worker-1:0,2`` keeps all of worker-0 and slots 0,2 of
    worker-1; exclusion removes listed slots (a bare hostname excludes the
    whole host). Include and exclude are mutually exclusive.
    """
    NODE_SEP, SLOT_LIST_START, SLOT_SEP = "@", ":", ","

    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive.")
    if not include_str and not exclude_str:
        return collections.OrderedDict(host_info)

    filtered_hosts: Dict[str, List[int]] = {}
    if include_str:
        parse_str = include_str
    else:
        filtered_hosts = deepcopy(dict(host_info))
        parse_str = exclude_str

    for node_config in parse_str.split(NODE_SEP):
        if SLOT_LIST_START in node_config:
            hostname, slots = node_config.split(SLOT_LIST_START)
            slot_ids = [int(x) for x in slots.split(SLOT_SEP)]
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            for s in slot_ids:
                if s not in host_info[hostname]:
                    raise ValueError(
                        f"No slot '{s}' specified on host '{hostname}'")
            if include_str:
                filtered_hosts[hostname] = slot_ids
            else:
                for s in slot_ids:
                    filtered_hosts[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            if include_str:
                filtered_hosts[hostname] = list(host_info[hostname])
            else:
                filtered_hosts[hostname] = []

    # dedup slots, drop empty hosts, restore hostfile ordering
    ordered = collections.OrderedDict()
    for host in host_info:
        if host in filtered_hosts and filtered_hosts[host]:
            ordered[host] = sorted(set(filtered_hosts[host]))
    return ordered


def parse_inclusion_exclusion(resource_pool: Dict[str, int], inclusion: str,
                              exclusion: str) -> "collections.OrderedDict":
    active = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode("utf-8")).decode("utf-8")


def decode_world_info(world_info_base64: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(world_info_base64))


def _resolve_coordinator(active_resources, args) -> str:
    if args.coordinator_addr:
        return args.coordinator_addr
    if getattr(args, "launcher", "").lower() == GCLOUD_LAUNCHER:
        # No direct ssh route to managed pod workers (that is the whole
        # point of the gcloud wrapper): defer resolution to the workers,
        # which read the coordinator from TPU_WORKER_HOSTNAMES.
        return "@pod-coordinator"
    first_host = next(iter(active_resources))
    if first_host in ("localhost", "127.0.0.1"):
        return "127.0.0.1"
    out = subprocess.check_output([f"ssh {first_host} hostname -I"], shell=True)
    addr = out.decode("utf-8").split()[0]
    logger.info(f"Using IP address of {addr} for node {first_host}")
    return addr


def _collect_exports(env) -> Dict[str, str]:
    exports = {}
    for var, val in env.items():
        if any(var.startswith(p) for p in EXPORT_ENV_PREFIXES):
            exports[var] = val
    for environ_path in [os.path.expanduser("~"), "."]:
        environ_file = os.path.join(environ_path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(environ_file):
            with open(environ_file) as fd:
                for line in fd:
                    line = line.strip()
                    if line and "=" in line:
                        key, val = line.split("=", 1)
                        exports[key] = val
    return exports


def main(args=None) -> int:
    args = parse_args(args)

    if (args.num_nodes >= 0 or args.num_chips >= 0) and \
            (args.include or args.exclude):
        raise ValueError("Cannot specify num_nodes/chips with include/exclude")

    resource_pool = None
    if args.tpu_pod:
        resource_pool = discover_tpu_pod()
        if resource_pool is None:
            logger.warning("--tpu_pod: no pod metadata found, falling back "
                           "to hostfile/local")
    if resource_pool is None:
        resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool is None and \
            args.launcher.lower() == GCLOUD_LAUNCHER:
        # Managed pod dispatch needs no hostfile — the pod's workers ARE
        # the topology. --num_nodes supplies the worker count (hostnames
        # are placeholders; workers rank themselves via TPU_WORKER_ID).
        if args.num_nodes <= 0:
            raise ValueError(
                "--launcher gcloud without a hostfile requires "
                "--num_nodes=<pod worker count>")
        # Slot count 0 = empty slot list = full chip visibility on each
        # worker (launch.py only masks TPU_VISIBLE_CHIPS for real slots).
        resource_pool = collections.OrderedDict(
            (f"worker-{i}", args.num_chips if args.num_chips > 0 else 0)
            for i in range(args.num_nodes))
    multi_node_exec = resource_pool is not None and len(resource_pool) > 0
    if not resource_pool:
        # local fallback: all chips of this host
        try:
            import jax
            device_count = jax.local_device_count()
        except Exception:
            device_count = 1
        resource_pool = collections.OrderedDict(localhost=max(1, device_count))
        args.coordinator_addr = args.coordinator_addr or "127.0.0.1"
        multi_node_exec = False

    if not multi_node_exec and args.num_nodes > 1:
        raise ValueError("num_nodes > 1 but no extra nodes via hostfile")

    active_resources = parse_inclusion_exclusion(resource_pool, args.include,
                                                 args.exclude)
    if args.num_nodes > 0:
        active_resources = collections.OrderedDict(
            list(active_resources.items())[:args.num_nodes])
    if args.num_chips > 0:
        active_resources = collections.OrderedDict(
            (h, list(range(args.num_chips))) for h in active_resources)

    env = os.environ.copy()
    coordinator = _resolve_coordinator(active_resources, args)
    world_info_base64 = encode_world_info(active_resources)
    multi_node_exec = args.force_multi or len(active_resources) > 1 or \
        args.launcher.lower() == GCLOUD_LAUNCHER   # always dispatch to pods

    if not multi_node_exec:
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={world_info_base64}",
               f"--coordinator_addr={coordinator}",
               f"--coordinator_port={args.coordinator_port}",
               f"--procs_per_node={args.procs_per_node}",
               "--node_rank=0",
               args.user_script] + args.user_args
    else:
        from .multinode_runner import (GcloudTPURunner, PDSHRunner,
                                       SSHRunner)
        if args.launcher.lower() == PDSH_LAUNCHER:
            runner = PDSHRunner(args, world_info_base64)
        elif args.launcher.lower() == SSH_LAUNCHER:
            runner = SSHRunner(args, world_info_base64)
        elif args.launcher.lower() == GCLOUD_LAUNCHER:
            runner = GcloudTPURunner(args, world_info_base64)
        else:
            raise NotImplementedError(f"Unknown launcher {args.launcher}")
        if not runner.backend_exists():
            raise RuntimeError(f"launcher '{args.launcher}' not installed")
        curr_path = os.path.abspath(".")
        env["PYTHONPATH"] = curr_path + (
            ":" + env["PYTHONPATH"] if "PYTHONPATH" in env else "")
        for key, val in _collect_exports(env).items():
            runner.add_export(key, val)
        cmd = runner.get_cmd(env, active_resources, coordinator)

    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
