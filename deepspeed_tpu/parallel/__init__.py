from .topology import (ProcessTopology, PipeDataParallelTopology,
                       PipeModelDataParallelTopology, PipelineParallelGrid,
                       build_mesh, DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS,
                       EP_AXIS, SLICE_AXIS)
from . import comm
from . import hlo_audit
from . import multislice
