"""Communication audit of compiled XLA programs.

The repo's ZeRO communication schedule — reduce-scatter(grads) → sharded
update → all-gather(params), the wire-volume win of ZeRO (Rajbhandari et
al., 2020) — is *declared* through GSPMD shardings (zero/partition.py) and
trusted to the SPMD partitioner (Xu et al., GSPMD 2021). Nothing about a
declaration guarantees the lowering: the known failure mode of declarative
ZeRO is the partitioner falling back to a full all-reduce + slice, which
materializes every gradient unpartitioned and doubles the wire bytes.

This module turns the schedule from prose into a checkable artifact:

- ``parse_hlo_collectives`` walks a compiled program's HLO text and
  extracts every collective (all-reduce, reduce-scatter, all-gather,
  collective-permute, all-to-all) with its shapes, byte volume, replica
  groups and enclosing computation (collectives inside a ``while`` body —
  a ``lax.scan`` — appear once; the caller multiplies by the analytic trip
  count, which the schedule oracle provides).
- ``CommAudit`` summarizes the ops and prices each with the standard ring
  wire model (all-reduce = 2(g-1)/g·B, reduce-scatter/all-gather =
  (g-1)/g·B, permute = B), the same model the analytic per-config
  expectations in tools/comm_audit.py use — so compiled reality and the
  paper's arithmetic are compared in the same currency.
- ``zero2_grad_sync_lowering`` is a cached capability probe (the
  tests/capability.py idiom): compile a minimal declared-reduce-scatter
  program once per (backend, mesh axis) and report whether THIS
  partitioner honors the declaration. The engine consults it to pick the
  guaranteed explicit ``lax.psum_scatter`` gradient path when the
  declarative one regresses.

Everything here is static analysis of ``jit(...).lower(...).compile()``
output — no step is executed, so auditing a multi-GB config costs only a
compile.

The generic HLO-text mechanics (computation splitting, shape sizing,
loop attribution, trip counts) live in ``analysis/hlo_text.py`` — the
shared parsing layer of the lint-pass framework (analysis/) — so the
collective audit and the lint suite read compiled programs identically.
This module keeps the COLLECTIVE-specific analysis: replica groups, the
ring wire model, the ZeRO-2 lowering probe, and the grad-sync pricing.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis.hlo_text import (
    DTYPE_BYTES, INSTR_RE as _INSTR_RE,
    parse_shape_bytes as _parse_shapes,
    split_computations as _split_computations,
    loop_computations as _loop_computations,
    while_trip_counts)

__all__ = [
    "CollectiveOp", "CommAudit", "parse_hlo_collectives", "audit_text",
    "audit_jit", "ring_wire_bytes", "zero2_grad_sync_lowering",
    "grad_sync_wire_model", "moe_alltoall_wire_model", "DTYPE_BYTES",
    "while_trip_counts",
]

COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "collective-permute", "all-to-all")

_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_LIST_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def ring_wire_bytes(kind: str, payload_bytes: int, group_size: int) -> int:
    """Per-participant wire bytes of one collective under the standard ring
    model — the currency the ZeRO paper's 2x claim is stated in:

    - all-reduce: 2(g-1)/g · B  (reduce-scatter phase + all-gather phase)
    - reduce-scatter / all-gather / all-to-all: (g-1)/g · B over the FULL
      (unscattered) buffer B
    - collective-permute: B (each source ships its buffer once)
    """
    g = max(1, group_size)
    if kind == "all-reduce":
        return 2 * (g - 1) * payload_bytes // g
    if kind in ("reduce-scatter", "all-gather", "all-to-all"):
        return (g - 1) * payload_bytes // g
    if kind == "collective-permute":
        return payload_bytes
    raise ValueError(f"unknown collective kind {kind!r}")


@dataclasses.dataclass
class CollectiveOp:
    kind: str                 # normalized (no -start suffix)
    name: str                 # HLO instruction name
    computation: str          # enclosing HLO computation ("" if unknown)
    out_bytes: int
    in_bytes: int
    out_shapes: List[str]
    in_shapes: List[str]
    group_size: int           # participants per replica group
    num_groups: int
    source_target_pairs: Optional[List[Tuple[int, int]]]
    op_name: str              # jax op metadata (attribution)
    in_loop: bool = False     # inside a while (lax.scan) body: executes
                              # once per trip, not once per step

    @property
    def payload_bytes(self) -> int:
        """The full (unscattered) buffer the wire model prices: the input
        for reduce-scatter (its output is the 1/g shard), the output for
        all-gather (its input is the shard), the buffer itself otherwise."""
        if self.kind == "reduce-scatter":
            return self.in_bytes
        return self.out_bytes

    @property
    def wire_bytes(self) -> int:
        if self.kind == "collective-permute":
            # A device only transmits if it appears as a source; shaped as
            # per-participating-device bytes.
            return self.out_bytes
        return ring_wire_bytes(self.kind, self.payload_bytes, self.group_size)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["wire_bytes"] = self.wire_bytes
        d["payload_bytes"] = self.payload_bytes
        return d


def parse_hlo_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Extract every collective instruction from optimized-HLO text.

    Handles both replica-group encodings XLA prints (`{{0,1,...}}` lists
    and the iota form `[G,g]<=[N]`), tuple-shaped variadic collectives,
    and async `-start`/`-done` pairs (only `-start` is counted). Each op
    records its enclosing computation and whether that computation is
    (transitively) a while-loop body. (Computation splitting and loop
    attribution come from the shared analysis/hlo_text layer.)"""
    comp_lines = _split_computations(hlo_text)
    loop_comps = _loop_computations(comp_lines)

    ops: List[CollectiveOp] = []
    for computation, lines in comp_lines.items():
        for line in lines:
            m = _INSTR_RE.match(line)
            if not m:
                continue
            op = m.group("op")
            is_async = op.endswith("-start")
            kind = op[:-6] if is_async else op
            if kind not in COLLECTIVE_KINDS:
                continue
            out_bytes, out_shapes = _parse_shapes(m.group("shape"),
                                                  largest_only=is_async)
            # Operands: everything inside the call parens up to the
            # matching close — `dtype[dims]{layout} %operand` pairs.
            rest = line[m.end():]
            depth, i = 1, 0
            while i < len(rest) and depth:
                if rest[i] == "(":
                    depth += 1
                elif rest[i] == ")":
                    depth -= 1
                i += 1
            in_bytes, in_shapes = _parse_shapes(rest[:i - 1])
            attrs = rest[i:]

            group_size, num_groups = 1, 1
            gm = _IOTA_GROUPS_RE.search(attrs)
            if gm:
                num_groups, group_size = int(gm.group(1)), int(gm.group(2))
            else:
                gm = _LIST_GROUPS_RE.search(attrs)
                if gm:
                    groups = [g for g in gm.group(1)[1:-1].split("},{")]
                    num_groups = len(groups)
                    group_size = max(
                        len([r for r in g.split(",") if r != ""])
                        for g in groups)
            pairs = None
            pm = _PAIRS_RE.search(attrs)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                        for p in pm.group(1)[1:-1].split("},{")]
                group_size = max(group_size, len(pairs))
            om = _OPNAME_RE.search(attrs)
            ops.append(CollectiveOp(
                kind=kind, name=m.group("name"), computation=computation,
                out_bytes=out_bytes, in_bytes=in_bytes,
                out_shapes=out_shapes, in_shapes=in_shapes,
                group_size=group_size, num_groups=num_groups,
                source_target_pairs=pairs,
                op_name=om.group(1) if om else "",
                in_loop=computation in loop_comps))
    return ops


@dataclasses.dataclass
class CommAudit:
    """Structured report over one compiled program's collectives."""
    ops: List[CollectiveOp]
    hlo_text: str = ""

    def while_trip_counts(self) -> List[int]:
        return while_trip_counts(self.hlo_text)

    def of_kind(self, kind: str) -> List[CollectiveOp]:
        return [o for o in self.ops if o.kind == kind]

    def in_loops(self, kind: Optional[str] = None) -> List[CollectiveOp]:
        """Collectives inside while-loop computations (scan bodies) — they
        execute once per trip, so their static bytes must be multiplied by
        the analytic trip count."""
        return [o for o in self.ops if o.in_loop
                and (kind is None or o.kind == kind)]

    def total_wire(self, kind: Optional[str] = None) -> int:
        return sum(o.wire_bytes for o in self.ops
                   if kind is None or o.kind == kind)

    def summary(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for o in self.ops:
            s = out.setdefault(o.kind, {"count": 0, "payload_bytes": 0,
                                        "wire_bytes": 0})
            s["count"] += 1
            s["payload_bytes"] += o.payload_bytes
            s["wire_bytes"] += o.wire_bytes
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"summary": self.summary(),
                "ops": [o.to_dict() for o in self.ops]}


def audit_text(hlo_text: str) -> CommAudit:
    return CommAudit(parse_hlo_collectives(hlo_text), hlo_text)


def audit_jit(fn, *args, **kwargs) -> CommAudit:
    """Audit a jitted callable on concrete (or ShapeDtypeStruct) args:
    lower → compile → parse. Compile-only; nothing executes."""
    import jax
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    compiled = fn.lower(*args, **kwargs).compile()
    return audit_text(compiled.as_text())


# --------------------------------------------------------------------- #
# The ZeRO-2 lowering probe + analytic wire model
# --------------------------------------------------------------------- #
_PROBE_CACHE: Dict[Tuple, str] = {}


def zero2_grad_sync_lowering(mesh, axis_name: str = "data",
                             dtype=None) -> str:
    """What a DECLARED dp-sharded gradient actually compiles to on this
    backend: ``"reduce-scatter"`` | ``"all-reduce"`` | ``"none"``.

    Compiles (never runs) a minimal replica of the engine's declarative
    ZeRO-2 pattern — batch sharded over ``axis_name``, grads constrained to
    a dp-sharded ``NamedSharding`` — and inspects which collective carries
    the cross-dp sync. "all-reduce" is the known GSPMD fallback (full
    all-reduce + slice): the gradient materializes unpartitioned and the
    wire bytes double vs the ZeRO schedule. Cached per (backend devices,
    axis, dtype) like tests/capability.py, so callers probe freely."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = dtype or jnp.float32
    n = int(mesh.shape[axis_name])
    if n <= 1:
        return "none"
    # The axis SIZE must be in the key: a dp=8 and a dp=4 x mp=2 mesh
    # enumerate the same device ids under the same axis name but compile
    # different probe programs.
    key = (tuple(d.id for d in mesh.devices.flat), axis_name, n,
           jnp.dtype(dtype).name)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]

    d = 2 * n
    w_sh = NamedSharding(mesh, P(axis_name))
    x_sh = NamedSharding(mesh, P(axis_name))

    def probe(w, x):
        g = jax.grad(lambda w_, x_: jnp.mean((x_ @ w_) ** 2))(w, x)
        return lax.with_sharding_constraint(g, w_sh)

    w = jax.ShapeDtypeStruct((d, d), dtype, sharding=NamedSharding(mesh, P()))
    x = jax.ShapeDtypeStruct((d, d), dtype, sharding=x_sh)
    try:
        audit = audit_jit(probe, w, x)
    except Exception:   # pragma: no cover - exotic backend
        _PROBE_CACHE[key] = "none"
        return "none"
    result = "none"
    if audit.of_kind("reduce-scatter"):
        result = "reduce-scatter"
    elif audit.of_kind("all-reduce"):
        result = "all-reduce"
    _PROBE_CACHE[key] = result
    return result


def moe_alltoall_wire_model(hidden: int, num_experts: int, top_k: int,
                            capacity_factor: float, ep: int,
                            n_moe_layers: int = 1, bytes_per_el: int = 4,
                            tokens_per_device: Optional[int] = None,
                            gas: int = 1) -> Dict[str, Any]:
    """Analytic per-device wire bytes of the MoE dispatch/combine
    all-to-alls (deepspeed_tpu/moe/layer.py) per optimizer step.

    Each MoE layer exchanges its ``[E, C, H]`` dispatch buffer over the
    ``expert`` axis FOUR times per micro-step — forward dispatch +
    combine, and their transposes in backward (the vjp of an all-to-all
    is an all-to-all) — each moving ``(ep-1)/ep`` of the buffer off-chip
    under the ring model (tools/comm_audit.py checks the compiled
    program against this to 5%).

    With ``tokens_per_device`` (T per micro-step) the figure is exact at
    the capacity rounding (C = ceil(cf·k·T/E)); without it only the
    T-free ``wire_bytes_per_token`` is reported (≈ 4·n·(ep-1)/ep·cf·k·
    H·bytes — the capacity ceil amortizes away). ep <= 1 prices to zero
    (no collective exists)."""
    out: Dict[str, Any] = {
        "ep": ep, "num_experts": num_experts, "top_k": top_k,
        "capacity_factor": capacity_factor, "n_moe_layers": n_moe_layers,
        "alltoalls_per_moe_layer_per_micro_step": 4,
        "bytes_per_el": int(bytes_per_el),
    }
    if ep <= 1:
        out.update({"wire_bytes_per_token": 0, "wire_bytes_per_step": 0})
        return out
    frac = (ep - 1) / ep
    import math as _math
    if _math.isinf(capacity_factor):
        per_token = 4 * n_moe_layers * frac * num_experts * hidden * \
            bytes_per_el
    else:
        per_token = 4 * n_moe_layers * frac * capacity_factor * top_k * \
            hidden * bytes_per_el
    out["wire_bytes_per_token"] = int(per_token)
    if tokens_per_device is not None:
        from ..moe.layer import expert_capacity
        c = expert_capacity(int(tokens_per_device), num_experts, top_k,
                            capacity_factor)
        buf = num_experts * c * hidden * bytes_per_el
        out["capacity"] = c
        out["dispatch_buffer_bytes"] = int(buf)
        out["wire_bytes_per_step"] = int(
            4 * n_moe_layers * int(gas) * ring_wire_bytes(
                "all-to-all", buf, ep))
    return out


def grad_sync_wire_model(params: Any, dp: int,
                         grad_bytes_per_el: int = 4,
                         zero3: bool = False,
                         param_bytes_per_el: Optional[int] = None,
                         gas: int = 1,
                         param_specs: Any = None,
                         mesh: Any = None,
                         moe: Optional[Dict[str, Any]] = None,
                         slices: int = 1,
                         dcn_compression: bool = False
                         ) -> Dict[str, Any]:
    """Analytic per-step gradient-sync wire bytes for a param tree under
    dp-way data parallelism, in both lowerings. Scatterable leaves follow
    zero/partition.py's rule (first dim >= dp and divisible); the rest are
    replicated and all-reduce in either mode (they are the small tail).

    ``zero3=True`` adds the stage-3 parameter-gather term: each sharded
    param crosses the wire twice more per micro-step — the forward
    all-gather and the backward re-gather (``jax.checkpoint`` around the
    gather / the layer scan's manual VJP re-gathers instead of saving
    the gathered tree) — at the COMPUTE dtype (``param_bytes_per_el``;
    the fp32 master shard is cast in flight, zero/stage3.gather_cast),
    each priced (g-1)/g · B by the ring model. With grad accumulation
    every micro-step repeats the whole schedule (the explicit path
    scatters into the sharded carry per micro-step too), the classic
    ZeRO-3 3x pattern: total = gas · (2 gathers + 1 fp32 grad
    reduce-scatter). ``param_specs`` overrides the sharded/replicated
    split with
    the engine's actual stage-3 spec tree (covered scanned leaves avoid
    the layer axis, so their divisibility differs from the plain rule);
    pass ``mesh`` with it so a dp+TP leaf is priced at its per-TP-rank
    slice (the dp collective moves 1/mp of the leaf per rank, and the
    dp gather reconstructs 1/mp per device, not the full leaf).

    ``moe``: kwargs for ``moe_alltoall_wire_model`` — when given, the
    output grows ``moe_alltoall_wire_bytes`` (the per-step priced
    dispatch/combine all-to-all term) and the full ``moe`` sub-record.
    The term is reported separately, NOT folded into the grad-sync
    figures: it is activation wire, and the engine sums the two for its
    per-step total.

    ``slices > 1``: the multi-slice HIERARCHICAL schedule
    (parallel/multislice.py) — the output grows the two-tier terms:

    - ``ici_wire_bytes``: the in-slice sync (reduce-scatter of
      scatterable + all-reduce of the replicated tail, over ``dp``) —
      identical to the single-slice reduce-scatter figure. Under
      ``zero3`` BOTH param gathers join this term (the axis-algebra
      planner binds them to `data`, an ICI axis on every
      factorization), per micro-step like the scatter;
    - ``dcn_payload_bytes``: the per-rank residual that crosses slices
      (the 1/dp shard + the replicated tail, f32);
    - ``dcn_wire_bytes``: its inter-slice ring all-reduce over
      ``slices`` — ONE per step (shards accumulate locally across
      micro-steps; only the accumulated residual crosses DCN);
    - ``dcn_wire_bytes_compressed``: the same hop in the 1-bit packed
      wire format (sign bits + per-chunk f32 scales,
      ops/onebit.comm_bytes) — what ``dcn_compression`` actually ships;
    - ``flat_dcn_link_bytes``: the comparator — a FLAT collective over
      the joint (slice, data) ring carries ~the full grad payload over
      every link including the DCN boundary links; hierarchy divides
      the DCN traffic by dp.

    The headline total ``hierarchical_wire_bytes`` = ici + dcn (the
    active dcn figure per ``dcn_compression``). With ``zero3`` the
    output also pins ``dcn_param_bytes: 0`` (zero param-sized bytes on
    the slow tier — the composition's claim) and carries the derived
    ``collective_plan`` (axis_algebra.plan_grad_sync) the audit and
    lint check the compiled program against.
    """
    import jax
    from .topology import DP_AXIS
    from ..runtime.zero.partition import _leaf_spec, spec_dp_dim

    leaves = jax.tree_util.tree_leaves(params)
    if param_specs is not None:
        spec_leaves = jax.tree_util.tree_structure(params).flatten_up_to(
            param_specs)
    else:
        spec_leaves = [None] * len(leaves)
    scatterable = replicated = 0
    scatterable_el = replicated_el = 0
    for leaf, sp in zip(leaves, spec_leaves):
        shape = getattr(leaf, "shape", None)
        if shape is None or getattr(leaf, "ndim", 0) < 1:
            continue
        nbytes = int(grad_bytes_per_el)
        nel = 1
        for s in shape:
            nbytes *= int(s)
            nel *= int(s)
        if sp is not None and mesh is not None:
            # dp+TP leaf: the dp collectives carry this TP rank's slice.
            for entry in sp:
                for ax in ((entry,) if isinstance(entry, str)
                           else (entry or ())):
                    if ax != DP_AXIS:
                        div = max(1, int(mesh.shape.get(ax, 1)))
                        nbytes //= div
                        nel //= div
        # The DP axis specifically: a leaf sharded only over a TP/model
        # axis never dp-scatters or dp-gathers (its dp grad sync is the
        # replicated all-reduce).
        sharded = spec_dp_dim(sp, DP_AXIS) is not None \
            if sp is not None \
            else any(e is not None for e in _leaf_spec(shape, dp, "data"))
        if sharded:
            scatterable += nbytes
            scatterable_el += nel
        else:
            replicated += nbytes
            replicated_el += nel
    repl_wire = ring_wire_bytes("all-reduce", replicated, dp)
    out = {
        "dp": dp,
        "grad_bytes": scatterable + replicated,
        "scatterable_bytes": scatterable,
        "replicated_bytes": replicated,
        "reduce_scatter_wire_bytes":
            ring_wire_bytes("reduce-scatter", scatterable, dp) + repl_wire,
        "all_reduce_wire_bytes":
            ring_wire_bytes("all-reduce", scatterable, dp) + repl_wire,
    }
    if zero3:
        pbytes = int(param_bytes_per_el or grad_bytes_per_el)
        gather_payload = scatterable_el * pbytes
        one_gather = ring_wire_bytes("all-gather", gather_payload, dp)
        out.update({
            "param_gather_payload_bytes": gather_payload,
            "param_gather_wire_bytes": 2 * int(gas) * one_gather,
            "param_gathers_per_step": 2 * int(gas),
            # Per STEP on the explicit path: every micro-step re-gathers
            # (fwd + bwd) and scatters its grads into the sharded carry.
            "zero3_wire_bytes":
                int(gas) * (out["reduce_scatter_wire_bytes"]
                            + 2 * one_gather),
        })
    if slices > 1:
        from .axis_algebra import MeshFactorization, plan_grad_sync
        from .multislice import dcn_comm_bytes
        fact = MeshFactorization.from_sizes(slice=slices, data=dp)
        plan = plan_grad_sync(fact, zero3=zero3,
                              dcn_compression=dcn_compression)
        # Per-rank residual after the in-slice reduce: the 1/dp shard of
        # every scatterable leaf + the replicated tail, f32. Stage-3
        # changes NOTHING here — its grads land on the same 1/dp shards
        # (gather_cast's transpose IS the in-slice reduce-scatter).
        dcn_el = scatterable_el // dp + replicated_el
        dcn_payload = dcn_el * 4
        dcn_wire = ring_wire_bytes("all-reduce", dcn_payload, slices)
        dcn_payload_c = dcn_comm_bytes(dcn_el, compressed=True,
                                       num_slices=slices)
        dcn_wire_c = ring_wire_bytes("all-reduce", dcn_payload_c, slices)
        active_dcn = dcn_wire_c if dcn_compression else dcn_wire
        # The in-slice (per-micro-step) tier: the grad reduce-scatter,
        # plus — under stage 3 — both param gathers, which the planner
        # places on `data`/ICI (param bytes NEVER ride DCN; the flat
        # comparator below shows what a joint-axis schedule would ship).
        ici = out["reduce_scatter_wire_bytes"]
        flat_link = scatterable + replicated
        if zero3:
            assert plan.gather is not None and plan.gather.tier == "ici"
            gather_payload = out["param_gather_payload_bytes"]
            ici += 2 * ring_wire_bytes("all-gather", gather_payload, dp)
            flat_link += 2 * gather_payload
        out.update({
            "slices": slices,
            "dcn_compression": bool(dcn_compression),
            "ici_wire_bytes": int(ici),
            "dcn_payload_bytes": int(dcn_payload),
            "dcn_wire_bytes": int(dcn_wire),
            "dcn_wire_bytes_compressed": int(dcn_wire_c),
            # A flat joint-(slice, data) ring pushes ~the full payload
            # over EVERY link, DCN boundary links included — under
            # stage 3 that payload includes BOTH param gathers per
            # micro-step, the figure the hierarchy zeroes out.
            "flat_dcn_link_bytes": int(flat_link),
            "dcn_param_bytes": 0,
            "hierarchical_wire_bytes": int(ici + active_dcn),
            "collective_plan": plan.to_meta(),
        })
    if moe is not None:
        m = moe_alltoall_wire_model(**moe)
        out["moe"] = m
        out["moe_alltoall_wire_bytes"] = int(
            m.get("wire_bytes_per_step") or 0)
    return out
