"""Axis algebra: one collective planner for every factored-mesh builder.

The mesh axes exist (`slice`, `pipe`, `expert`, `data`, `seq`, `model` —
parallel/topology.build_mesh) but until this module the COMPOSITIONS
were hand-cased pairs: the explicit grad builder resolved its outer
axis with an if/elif ladder, `multislice.classify_two_tier` re-derived
the same group-signature arithmetic, the wire model asserted stage-3
out of multi-slice meshes, and the collective_placement lint pass
pattern-matched per pair. Each new axis pair meant touching all four.

This module is the single derivation. From the mesh factorization alone
(per-axis sizes) plus the ZeRO stage, it answers:

- **scope**: which axes the explicit grad builder's ``shard_map`` binds
  (``grad_shard_scope`` — the replica axes the batch shards over);
- **schedule**: which axis each collective binds and where it sits
  (``plan_grad_sync`` — param gathers and grad scatters on the
  innermost replica axis, in-scan, once per micro-step; the accumulated
  1/dp residual on the single OUTER replica axis, once per step);
- **tier**: which wire each axis rides (``tier`` — the `slice` axis is
  the only DCN axis; everything else is in-slice ICI), which is what
  makes the headline composition fall out of the algebra instead of a
  new special case: under ZeRO-3 the param all-gathers bind `data`, and
  `data` is an ICI axis on EVERY factorization — so stage-3 across
  slices gathers over ICI only and never puts a param-sized byte on
  DCN;
- **classification**: which tier a compiled collective's replica group
  signature implies (``classify_group`` — the heuristic that used to
  live in multislice.py, now stated once for audits AND lint).

The planner is deliberately mesh-level: the per-LEAF rule (which dim a
given leaf shards/scatters on) stays in ``runtime/zero/partition``
(`_leaf_spec` / `spec_dp_dim` / `stage3_param_specs`) — the algebra
here composes axes, not shapes.

Unsupported compositions raise here, with the structural reason, so the
engine's refusals quote the planner instead of maintaining their own
blocker folklore (`MeshFactorization.outer_axis` on a slice×expert
mesh: the residual hop supports exactly one outer axis today).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .topology import (DP_AXIS, EP_AXIS, MP_AXIS, PP_AXIS, SLICE_AXIS,
                       SP_AXIS)

__all__ = ["REPLICA_AXES", "DCN_AXES", "MeshFactorization",
           "CollectiveStep", "GradSyncPlan", "plan_grad_sync"]

# Grad-replica axes, outermost -> innermost: a gradient is summed over
# exactly these. `data` is the innermost (the ZeRO shard axis); at most
# one OUTER replica axis may be live per build (the residual hop).
REPLICA_AXES: Tuple[str, ...] = (SLICE_AXIS, EP_AXIS, DP_AXIS)

# Axes whose hops leave the ICI domain. Everything not listed is
# in-slice by construction (build_mesh keeps `slice` outermost, so one
# slice's devices stay contiguous on the fast tier).
DCN_AXES: Tuple[str, ...] = (SLICE_AXIS,)

_CANONICAL = (SLICE_AXIS, PP_AXIS, EP_AXIS, DP_AXIS, SP_AXIS, MP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshFactorization:
    """Per-axis sizes of a (possibly virtual) device mesh, as the
    planner's sole input. Hashable and mesh-library-free so plans can
    be derived from lint meta / audit records as well as live meshes."""

    axis_sizes: Tuple[Tuple[str, int], ...]

    @classmethod
    def from_mesh(cls, mesh) -> "MeshFactorization":
        return cls.from_sizes(**{a: int(s) for a, s in mesh.shape.items()})

    @classmethod
    def from_sizes(cls, **sizes: int) -> "MeshFactorization":
        for a in sizes:
            if a not in _CANONICAL:
                raise ValueError(f"unknown mesh axis {a!r} (known: "
                                 f"{_CANONICAL})")
        return cls(tuple((a, int(sizes.get(a, 1))) for a in _CANONICAL))

    # ---- lookups --------------------------------------------------- #
    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.axis_sizes)

    def size(self, axis: str) -> int:
        return self.shape.get(axis, 1)

    @property
    def slices(self) -> int:
        return self.size(SLICE_AXIS)

    @property
    def ep(self) -> int:
        return self.size(EP_AXIS)

    @property
    def dp(self) -> int:
        return self.size(DP_AXIS)

    @property
    def replicas(self) -> int:
        """Grad replica count — the mean-correction divisor."""
        n = 1
        for a in REPLICA_AXES:
            n *= self.size(a)
        return n

    # ---- the algebra ----------------------------------------------- #
    def tier(self, axis: str) -> str:
        """Which wire a collective bound to ``axis`` rides."""
        return "dcn" if axis in DCN_AXES else "ici"

    @property
    def live_replica_axes(self) -> Tuple[str, ...]:
        """Replica axes of size > 1, outermost first. `data` is always
        included: it is the shard axis even at dp == 1 (degenerate
        collectives are free)."""
        return tuple(a for a in REPLICA_AXES
                     if self.size(a) > 1 or a == DP_AXIS)

    @property
    def outer_axis(self) -> Optional[str]:
        """The single replica axis OUTSIDE `data` carrying the
        once-per-step residual hop, or None on a plain dp mesh. Raises
        when more than one outer replica axis is live — the hierarchical
        schedule (accumulate 1/dp shards locally, one residual
        all-reduce at step end) composes exactly one outer axis today;
        slice×expert needs a chained residual schedule that does not
        exist yet."""
        outer = [a for a in REPLICA_AXES[:-1] if self.size(a) > 1]
        if len(outer) > 1:
            raise ValueError(
                "unsupported mesh factorization: more than one outer "
                f"replica axis is live ({' x '.join(outer)}); the "
                "hierarchical grad sync carries its once-per-step "
                "residual over exactly one axis outside 'data'")
        return outer[0] if outer else None

    @property
    def batch_axes(self) -> Tuple[str, ...]:
        """Axes the global batch shards over jointly — also the
        explicit grad builder's shard_map scope (``grad_shard_scope``)."""
        outer = self.outer_axis
        return (outer, DP_AXIS) if outer else (DP_AXIS,)

    @property
    def grad_shard_scope(self) -> Tuple[str, ...]:
        return self.batch_axes

    def classify_group(self, group_size: int) -> str:
        """Tier implied by a compiled collective's replica-group SIZE
        (the HLO parser records sizes, not member ids): on a factored
        replica mesh with the outer axis outermost, inner collectives
        form ``outer`` groups of ``dp`` consecutive members and outer
        collectives form ``dp`` groups of ``outer`` strided members —
        so group == dp ⇒ the inner tier, group == outer ⇒ the outer
        axis's tier, group == outer*dp ⇒ a FLAT joint-axis collective
        (every byte crosses the slow tier: the violation). Ambiguous
        when outer == dp; audits pick shapes where they differ."""
        outer = self.outer_axis
        osize = self.size(outer) if outer else 1
        if osize > 1 and osize == self.dp:
            raise ValueError(
                "tier classification by group signature is ambiguous "
                f"when the outer axis size equals dp (= {self.dp}); "
                "audit on a mesh where they differ")
        if osize > 1 and group_size == osize * self.dp:
            return "flat"
        if group_size == self.dp:
            return "ici"
        if osize > 1 and group_size == osize:
            return self.tier(outer)
        return "other"


# --------------------------------------------------------------------- #
# The derived collective schedule
# --------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class CollectiveStep:
    """One collective in a derived schedule: what it is, which axis it
    binds, which wire that axis rides, and where it sits."""
    op: str               # all-gather | reduce-scatter | all-reduce
    axis: str             # mesh axis name
    tier: str             # ici | dcn
    placement: str        # in-scan (per micro-step) | per-step
    payload: str          # human label: what crosses the wire

    def to_meta(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class GradSyncPlan:
    """The explicit path's full per-step collective schedule for one
    mesh factorization + ZeRO stage, as derived structure: builders
    execute it, the wire model prices it, lint and the comm audit
    check the compiled program against it."""
    fact: MeshFactorization
    steps: Tuple[CollectiveStep, ...]

    def _only(self, op: str) -> Optional[CollectiveStep]:
        hits = [s for s in self.steps if s.op == op]
        return hits[0] if hits else None

    @property
    def gather(self) -> Optional[CollectiveStep]:
        """The ZeRO-3 param all-gather (None below stage 3)."""
        return self._only("all-gather")

    @property
    def scatter(self) -> CollectiveStep:
        """The in-scan grad reduce-scatter."""
        return self._only("reduce-scatter")

    @property
    def residual(self) -> Optional[CollectiveStep]:
        """The once-per-step outer-axis residual hop (None on a plain
        dp mesh)."""
        return self._only("all-reduce")

    def to_meta(self) -> List[Dict[str, str]]:
        return [s.to_meta() for s in self.steps]

    def describe(self) -> str:
        return "; ".join(
            f"{s.op}[{s.axis}/{s.tier}, {s.placement}: {s.payload}]"
            for s in self.steps)


def plan_grad_sync(fact: MeshFactorization, *, zero3: bool = False,
                   dcn_compression: bool = False) -> GradSyncPlan:
    """Derive the explicit grad-sync schedule from the factorization.

    The derivation, not a case table:

    - params/grads shard over the INNERMOST replica axis (`data`), so
      the ZeRO-3 gathers and the grad reduce-scatter bind `data` —
      whose tier is ICI on every factorization (DCN_AXES) — and sit
      inside the gas scan (each micro-step re-gathers and scatters into
      the sharded carry);
    - the accumulated 1/dp residual sums over the single OUTER replica
      axis once per step; its tier is whatever that axis rides (`slice`
      ⇒ DCN, `expert` ⇒ in-slice ICI), and only the DCN hop may be
      1-bit compressed.

    Hence the headline composition for free: slices×ZeRO-3 plans param
    gathers as in-scan ICI steps and a residual-sized DCN hop — never a
    param-sized byte on the slow tier.
    """
    steps: List[CollectiveStep] = []
    if zero3:
        steps.append(CollectiveStep(
            "all-gather", DP_AXIS, fact.tier(DP_AXIS), "in-scan",
            "param shards -> compute dtype (fwd + bwd re-gather)"))
    steps.append(CollectiveStep(
        "reduce-scatter", DP_AXIS, fact.tier(DP_AXIS), "in-scan",
        "f32 grads -> owning 1/dp shard"))
    outer = fact.outer_axis
    if outer is not None:
        tier = fact.tier(outer)
        wire = "accumulated 1/dp residual"
        if dcn_compression and tier == "dcn":
            wire += " (1-bit error-feedback wire)"
        steps.append(CollectiveStep("all-reduce", outer, tier,
                                    "per-step", wire))
    return GradSyncPlan(fact, tuple(steps))
