"""Distributed bootstrap and thin collective API.

Parity with reference ``utils/distributed.py`` (init_distributed w/ NCCL
default + MPI env discovery) and ``runtime/pipe/p2p.py`` (2-rank broadcast
p2p). TPU-native mapping:

- bootstrap = ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` driven by env vars the launcher sets;
- collectives = XLA ops over *named mesh axes* usable under ``shard_map``:
  ``all_reduce (psum)``, ``reduce_scatter (psum_scatter)``, ``all_gather``,
  ``broadcast``, ``permute (ppermute)``. The reference's
  p2p-as-2-rank-broadcast trick becomes ``ppermute``, which rides ICI
  directly and is strictly better.

Upper layers (engine, ZeRO, pipeline) only use this module, keeping them
backend-agnostic the way the reference's layers only use torch.distributed.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger

_INITIALIZED = False


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None,
              axis_names=None, **kw):
    """Version-portable ``shard_map``: newer jax exposes ``jax.shard_map``
    (kwargs ``check_vma`` and ``axis_names`` = the manual axes); older
    releases ship it under ``jax.experimental.shard_map`` where the same
    knobs are ``check_rep`` and the complementary ``auto`` set. Every
    in-repo caller routes through here."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    # Older jax's replication checker (check_rep) predates pcast/pvary, so
    # kernels that mark varying carries with the new API can never satisfy
    # it — disable it by default there (it is a static analysis only).
    kw["check_rep"] = bool(check_vma) if check_vma is not None else False
    if axis_names is not None:
        auto = {a for a in frozenset(mesh.axis_names) - frozenset(axis_names)
                if mesh.shape[a] > 1}
        if auto:
            # Partial-auto (manual pipe/seq axis + GSPMD dp/mp inside) is
            # where old-jax support ends: its experimental `auto=` path
            # CHECK-fails in XLA on these programs. Fail with a real
            # message instead of aborting the interpreter.
            raise NotImplementedError(
                f"this jax ({jax.__version__}) cannot run a partially-"
                f"manual shard_map (manual {sorted(axis_names)} + auto "
                f"{sorted(auto)} axes); upgrade jax or set the auto axes "
                "to size 1")
        # All residual axes are size 1: run fully manual (equivalent).
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def axis_in_scope(axis_name: str) -> bool:
    """True when ``axis_name`` is bound as a MANUAL axis at the current
    trace point (i.e. we are inside a shard_map/pmap over it, so
    ``lax.psum(axis_name)`` / ``lax.all_to_all(axis_name)`` are legal
    directly). Layers that normally wrap themselves in their own
    shard_map (the MoE FFN) use this to detect they are ALREADY inside
    one — the engine's factored explicit-gradient path runs the whole
    loss under a fully-manual shard_map over (expert, data) — and run
    their collectives bare instead of nesting. Version-portable: probes
    the axis env through whichever introspection this jax exposes;
    an un-probe-able jax answers False (callers then take the
    self-wrapping path, which is always correct outside a shard_map)."""
    try:
        from jax import core
        if hasattr(core, "axis_frame"):            # jax <= 0.4.x
            core.axis_frame(axis_name)
            return True
        if hasattr(core, "get_axis_env"):          # newer jax
            return core.get_axis_env().axis_exists(axis_name)
    except NameError:
        return False
    except Exception:
        pass
    try:
        from jax import core
        return axis_name in core.unsafe_get_axis_names_DO_NOT_USE()
    except Exception:
        return False


def pvary(x, axis_name):
    """Mark ``x`` as varying over a manual mesh axis. New jax spells this
    ``lax.pcast(..., to="varying")``; older releases have no such marking
    (their shard_map rep-checker is disabled above), so it is identity."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


def init_distributed(dist_backend: str = "xla", distributed_port: int = 29500,
                     verbose: bool = True, init_method: Optional[str] = None) -> None:
    """Bring up the multi-host JAX runtime if env says we're multi-process.

    Env contract (set by deepspeed_tpu.launcher, mirrors the reference's
    MASTER_ADDR/RANK/WORLD_SIZE contract at launch.py:103-118):
    ``DS_COORDINATOR_ADDRESS``, ``DS_NUM_PROCESSES``, ``DS_PROCESS_ID``.
    Falls back to JAX's own cluster auto-detection; single-process otherwise.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = init_method or os.environ.get("DS_COORDINATOR_ADDRESS")
    nprocs = os.environ.get("DS_NUM_PROCESSES")
    pid = os.environ.get("DS_PROCESS_ID")
    if coord and nprocs and int(nprocs) > 1:
        if verbose:
            logger.info(f"Initializing JAX distributed: coordinator={coord} "
                        f"num_processes={nprocs} process_id={pid}")
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(nprocs),
                                   process_id=int(pid) if pid is not None else None)
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size() -> int:
    return jax.device_count()

def get_local_device_count() -> int:
    return jax.local_device_count()

def get_process_index() -> int:
    return jax.process_index()

def get_process_count() -> int:
    return jax.process_count()


# --------------------------------------------------------------------- #
# Collectives over named mesh axes — call ONLY inside shard_map/pmap.
# --------------------------------------------------------------------- #
def all_reduce(x: Any, axis_name: str, op: str = "sum") -> Any:
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"Unsupported all_reduce op {op}")


def reduce_scatter(x: Any, axis_name: str, scatter_dimension: int = 0,
                   tiled: bool = True) -> Any:
    """Sum-reduce then scatter shards along `scatter_dimension`."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=tiled)


def all_gather(x: Any, axis_name: str, axis: int = 0, tiled: bool = True) -> Any:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x: Any, axis_name: str, split_axis: int, concat_axis: int,
               tiled: bool = True) -> Any:
    """Exchange: split ``split_axis`` across the axis group, concatenate
    the received pieces on ``concat_axis``. Tiled (the default) keeps the
    rank; untiled requires ``split_axis`` to equal the axis size and
    unstacks it. Applied twice with ``split_axis == concat_axis`` it is
    an involution — the identity the MoE combine path relies on
    (deepspeed_tpu/moe/layer.py).

    The operand is marked varying over the axis first (``pvary`` — the
    same shard_map rep-checker shim its collective siblings got):
    new-jax's vma analysis requires an all-to-all input to be
    per-member-varying, and a replicated-marked operand would be
    rejected; on old jax the marking is identity."""
    return lax.all_to_all(pvary(x, axis_name), axis_name,
                          split_axis=split_axis, concat_axis=concat_axis,
                          tiled=tiled)


def broadcast(x: Any, axis_name: str, src: int = 0) -> Any:
    """Every member receives src's value (reference p2p/broadcast parity)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def permute(x: Any, axis_name: str, perm: Sequence[Tuple[int, int]]) -> Any:
    """Point-to-point pattern as a collective-permute.

    The reference implements stage p2p as dist.broadcast on 2-rank groups
    (p2p.py:31-55); ppermute expresses the same dataflow natively on ICI.
    """
    return lax.ppermute(x, axis_name, perm=list(perm))


def send_to_next(x: Any, axis_name: str, axis_size: int) -> Any:
    """Rotate +1 along the axis ring (pipeline activations)."""
    return permute(x, axis_name, [(i, (i + 1) % axis_size) for i in range(axis_size)])


def send_to_prev(x: Any, axis_name: str, axis_size: int) -> Any:
    """Rotate -1 along the axis ring (pipeline gradients)."""
    return permute(x, axis_name, [(i, (i - 1) % axis_size) for i in range(axis_size)])


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def sparse_all_reduce(dense_grads_by_rank):
    """Host-side sparse (CSR) allreduce of row-sparse gradients.

    Parity with the engine's CSR embedding-gradient allreduce (reference
    engine.py:1197-1253: sparse grads are shipped as values+indices and
    re-densified after the gather). Inside jit, XLA reduces dense tensors
    over ICI and there is nothing to save; this host path is for
    DCN-bounded exchanges (multi-slice sync, elastic state shipping) where
    the wire volume is ``nnz_rows/vocab`` of the dense tensor.

    ``dense_grads_by_rank``: list of [rows, cols] arrays (one per rank).
    Returns (dense_sum, sparse_elements_shipped, dense_elements).
    """
    from ..runtime.csr_tensor import CSRTensor, all_gather_csr
    shards = [CSRTensor.from_dense(g) for g in dense_grads_by_rank]
    total = all_gather_csr(shards)
    shipped = sum(s.sparse_size() for s in shards)
    return total.to_dense(), shipped, total.dense_size


def csr_exchange_hosts(csr):
    """Cross-process CSR allgather: size gather → pad every shard to the
    max row count → allgather indices+values → trim → coalesce. Mirrors the
    reference's ``csr_all_gather`` padding protocol (engine.py:1234-1253)
    over the jax.distributed host channel; this is the DCN wire format
    whose volume is what sparse gradients exist to save.
    """
    import numpy as np
    from jax.experimental import multihost_utils
    from ..runtime.csr_tensor import CSRTensor, all_gather_csr
    n = np.asarray([csr.row_indices.shape[0]], np.int32)
    sizes = np.asarray(multihost_utils.process_allgather(n)).reshape(-1)
    mx = max(1, int(sizes.max()))
    pad = mx - int(n[0])
    idx = np.pad(csr.row_indices, (0, pad))
    vals = np.pad(np.asarray(csr.values, np.float32), ((0, pad), (0, 0)))
    all_idx = np.asarray(multihost_utils.process_allgather(idx))
    all_vals = np.asarray(multihost_utils.process_allgather(vals))
    shards = [CSRTensor(all_idx[p][:sizes[p]], all_vals[p][:sizes[p]],
                        csr.dense_shape)
              for p in range(sizes.shape[0]) if sizes[p] > 0]
    if not shards:
        return csr
    return all_gather_csr(shards)


def host_allreduce_sum(x: float) -> float:
    """Sum a host-side scalar across processes over the jax.distributed
    channel (the cross-rank reduction the partitioned offload grad norm
    needs, reference stage2.py:1371-1411)."""
    import numpy as np
    if jax.process_count() == 1:
        return float(x)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(
        np.asarray([x], np.float32))
    return float(np.sum(np.asarray(gathered, np.float64)))
