"""Multi-slice scale-out: hierarchical ICI/DCN gradient sync.

A production TPU pod is not one ICI domain: it is many slices (each a
torus of chips on fast ICI) joined by the data-center network (DCN),
whose per-chip bandwidth is one to two orders of magnitude below ICI
(monitor/peaks.py's two-tier table). Everything in-tree up to now
assumed one slice; this module holds the slice-aware collective
schedule the engine's explicit gradient path composes:

1. **In-slice reduce-scatter over ICI** (``in_slice_reduce``): each
   gradient leaf ``lax.psum_scatter``s over the ``data`` axis at its
   declared ZeRO partition dim — exactly the single-slice explicit
   ZeRO-2 schedule, confined to the fast tier.
2. **Inter-slice all-reduce over DCN** (``inter_slice_allreduce``): the
   1/dp-sharded residual — and ONLY the residual — all-reduces over the
   ``slice`` axis. A flat sync over the joint (slice, data) group would
   push grad-sized traffic across every DCN boundary link; the
   hierarchy pushes 1/dp of that (the collective_placement lint pass
   gates the compiled program on exactly this).
3. Optionally, the DCN hop alone is **1-bit compressed**
   (``zero_optimization.dcn_compression``): each slice error-feedback
   sign-compresses its shard contribution (``ops/onebit._compress`` —
   the same ``scale * sign(compensated)`` wire format 1-bit Adam uses)
   before the inter-slice sum. Like the 1-bit Adam flagship, the
   in-XLA emulation psums the DECOMPRESSED values at full precision;
   the DCN wire format the pricing is about is packed sign bits + one
   f32 scale per chunk (``dcn_comm_bytes``), ~1/32 of the f32 volume.
   The ICI hop is never compressed — it is not the bottleneck.

The per-step loss-mean/grad-mean correction divides by the FULL replica
count (slices * dp), exact for power-of-two worlds — which is what makes
ONE 2-slice step on a slice-duplicated batch BIT-identical to the
1-slice step from the same state (tests/test_multislice.py: the
hierarchical sync sums two bitwise-equal in-slice partials, an exact
power-of-two scaling; multi-step trajectories meet the usual few-ulp
cross-program FMA limit, which the sync contributes nothing to).

Emulation honesty: on the CPU dev mesh "slices" are just an outer mesh
axis over virtual devices — every collective actually rides host
memory. What the tests/audits pin is STRUCTURAL: which collectives
exist, their replica groups, their payload bytes, and bit-parity of the
numerics. Real DCN wall-clock needs a real multislice pod.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .topology import DP_AXIS, SLICE_AXIS

__all__ = ["SliceTopology", "in_slice_reduce", "inter_slice_allreduce",
           "dcn_comm_bytes", "dcn_compression_ratio", "classify_two_tier",
           "two_tier_wire_summary"]


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Resolved (slices, dp-per-slice) layout of a mesh / emulated world."""
    num_slices: int
    dp_per_slice: int

    @property
    def replicas(self) -> int:
        return self.num_slices * self.dp_per_slice

    @classmethod
    def from_mesh(cls, mesh) -> "SliceTopology":
        return cls(num_slices=int(mesh.shape.get(SLICE_AXIS, 1)),
                   dp_per_slice=int(mesh.shape.get(DP_AXIS, 1)))


# --------------------------------------------------------------------- #
# The two collective tiers — call ONLY inside shard_map over the axes.
# --------------------------------------------------------------------- #
def in_slice_reduce(g, dp_dim: Optional[int], *, dp_axis: str = DP_AXIS):
    """Tier 1 (ICI): f32-widen then reduce over the in-slice ``data``
    axis — ``psum_scatter`` at the declared ZeRO partition dim, plain
    ``psum`` for non-divisible (replicated) leaves. The widen-BEFORE-
    collective ordering matches the single-slice explicit path, so the
    in-slice partial is bitwise the single-slice reduction."""
    import jax.numpy as jnp
    from jax import lax
    g = g.astype(jnp.float32)
    if dp_dim is None:
        return lax.psum(g, dp_axis)
    return lax.psum_scatter(g, dp_axis, scatter_dimension=dp_dim,
                            tiled=True)


def inter_slice_allreduce(g_shard, error=None, *, num_slices: int,
                          slice_axis: str = SLICE_AXIS,
                          compress: bool = False):
    """Tier 2 (DCN): all-reduce the in-slice-reduced 1/dp shard across
    slices. With ``compress``, each slice transmits the error-feedback
    1-bit form ``scale * sign(shard + error)`` (per-chunk L1 scales,
    ``ops/onebit._compress``) and the psum sums the transmitted values —
    the single-program emulation of the packed-sign DCN exchange.
    Returns ``(summed, new_error)``; ``new_error`` is None when not
    compressing (callers thread it back into the carried state only
    when compression is live)."""
    from jax import lax
    if not compress:
        return lax.psum(g_shard, slice_axis), None
    from ..ops.onebit import _compress
    if error is None:
        raise ValueError("dcn compression needs the carried error-"
                         "feedback buffer (pass error=...)")
    sent, new_error = _compress(g_shard, error, chunks=num_slices)
    return lax.psum(sent, slice_axis), new_error


# --------------------------------------------------------------------- #
# The DCN wire format (pricing — what the compiled emulation cannot show)
# --------------------------------------------------------------------- #
def dcn_comm_bytes(n_elements: int, *, compressed: bool,
                   num_slices: int = 2) -> int:
    """Per-slice-per-hop DCN payload for one shard exchange of
    ``n_elements`` f32 values: 4 B/element dense, or the 1-bit packed
    format (1 sign bit/element + one f32 scale per chunk, chunks =
    num_slices) — ``ops/onebit.comm_bytes``, the same wire format the
    1-bit Adam claims are stated in."""
    from ..ops.onebit import comm_bytes
    return comm_bytes(n_elements, compressed=compressed,
                      chunks=num_slices)


def dcn_compression_ratio(n_elements: int, num_slices: int = 2) -> float:
    """dense/compressed DCN payload ratio (→ ~32x for f32 at flagship
    shard sizes; the ≥8x acceptance floor holds down to ~100-element
    shards)."""
    return dcn_comm_bytes(n_elements, compressed=False,
                          num_slices=num_slices) / \
        dcn_comm_bytes(n_elements, compressed=True, num_slices=num_slices)


# --------------------------------------------------------------------- #
# Two-tier classification of a compiled program's collectives
# --------------------------------------------------------------------- #
def classify_two_tier(ops: List[Any], num_slices: int, dp: int,
                      min_payload_bytes: int = 64
                      ) -> Dict[str, List[Any]]:
    """Split audited collectives (``hlo_audit.CollectiveOp``) into the
    tier their replica groups ride.

    The group-signature heuristic itself now lives in the axis-algebra
    planner (``axis_algebra.MeshFactorization.classify_group`` — stated
    once for audits AND the collective_placement lint); this function
    is the list-level wrapper audits call. Ambiguous when slices == dp
    (raises); callers (tools/comm_audit.py, the tier-1 gate) pick
    slices != dp. Scalar bookkeeping psums below ``min_payload_bytes``
    are ignored."""
    from .axis_algebra import MeshFactorization
    fact = MeshFactorization.from_sizes(slice=num_slices, data=dp)
    fact.classify_group(dp)     # raise the ambiguity eagerly, ops or not
    out: Dict[str, List[Any]] = {"ici": [], "dcn": [], "flat": [],
                                 "other": []}
    for o in ops:
        if o.payload_bytes < min_payload_bytes:
            continue
        out[fact.classify_group(o.group_size)].append(o)
    return out


def two_tier_wire_summary(ops: List[Any], num_slices: int, dp: int,
                          min_payload_bytes: int = 64) -> Dict[str, int]:
    """Per-tier compiled wire-byte totals (ring model, via each op's
    ``wire_bytes``) — the figure the comm audit compares to the analytic
    two-tier model."""
    tiers = classify_two_tier(ops, num_slices, dp, min_payload_bytes)
    return {k: int(sum(o.wire_bytes for o in v)) for k, v in tiers.items()}
