"""Named-axis process/device topology and mesh construction.

Parity with reference ``runtime/pipe/topology.py``:
- ``ProcessTopology`` (topology.py:12-232): named-axis cartesian rank↔coord
  mapping, axis comm lists, coordinate filtering.
- ``PipeDataParallelTopology`` (topology.py:235), ``PipeModelDataParallelTopology``
  (topology.py:246-250): canonical 2-/3-axis layouts.
- ``PipelineParallelGrid`` (topology.py:252-455): the "mpu" contract —
  ``get_{data,model,pipe}_parallel_{rank,world_size,group}``.

TPU-native delta: a topology also materializes as a ``jax.sharding.Mesh``
(``build_mesh``) whose axis order puts the fastest-varying (model-parallel)
axis innermost so its collectives ride the shortest ICI paths; groups are
mesh axes, not torch process groups.
"""
from __future__ import annotations

from collections import namedtuple
from itertools import product
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class ProcessTopology:
    """Cartesian product of named axes; axis 0 is outermost (row-major)."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        assert len(axes) == len(dims), "axes and dims must align"
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict[Any, int] = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {coord_kwargs}")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {coord_kwargs} not in topology"
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes: Sequence[str] = ("data", "pipe"),
                      inner_sep: str = "_", outer_sep: str = "-") -> str:
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that vary along `axis` with all others fixed."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists: List[List[int]] = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in product(*ranges):
            other = dict(zip(other_axes, coord))
            sub = [self.get_rank(**{axis: i}, **other) for i in range(self.get_dim(axis))]
            lists.append(sub)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all given axis=value filters."""
        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return sorted(rank for coord, rank in self.mapping.items() if _match(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        axis_num = self.axes.index(axis)
        return sorted(rank for coord, rank in self.mapping.items() if coord[axis_num] == idx)

    def world_size(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def __str__(self) -> str:
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """(pipe, data) — adjacent pipe stages map to adjacent device coords."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D parallelism (pipe, data, model); model innermost so tensor-parallel
    collectives stay on the tightest ICI neighborhood."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


# --------------------------------------------------------------------- #
# Mesh construction
# --------------------------------------------------------------------- #
# Canonical mesh axis names used across the framework.
DP_AXIS = "data"
MP_AXIS = "model"
PP_AXIS = "pipe"
SP_AXIS = "seq"
# Expert parallelism (MoE): the `expert` axis FACTORS OUT OF data — it
# reuses the data-parallel devices, so the batch shards over
# (expert, data) jointly and the total replica count is ep * dp. Expert
# FFN weights shard over `expert` (each group owns E/ep experts) and
# their grads sync over `data` WITHIN an expert group only; the MoE
# all-to-all dispatch/combine rides this axis (deepspeed_tpu/moe/).
EP_AXIS = "expert"
# Multi-slice scale-out: the `slice` axis is OUTERMOST — members of one
# slice are joined by fast ICI, distinct slices only by slow DCN. Data
# parallelism factors WITHIN a slice (the batch shards over
# (slice, data) jointly, replica count = slices * dp), ZeRO shards over
# `data` within a slice, and gradient sync is HIERARCHICAL: in-slice
# reduce-scatter over ICI, then an inter-slice all-reduce over DCN that
# moves only the 1/dp-sharded residual (parallel/multislice.py).
#
# NOT to be confused with the reference's "slice parallel" accessors on
# PipelineParallelGrid below, which alias MODEL (tensor-slicing)
# parallelism and are deprecated under that name.
SLICE_AXIS = "slice"


def build_mesh(dp: Optional[int] = None, mp: int = 1, pp: int = 1, sp: int = 1,
               ep: int = 1, slices: int = 1, devices=None,
               axis_order: Tuple[str, ...] = (SLICE_AXIS, PP_AXIS, EP_AXIS,
                                              DP_AXIS, SP_AXIS, MP_AXIS)):
    """Build a ``jax.sharding.Mesh`` with named axes over available devices.

    dp=None infers the remainder of the device count. Axis order places mp
    innermost (fastest-varying) for the shortest ICI hops, pp outermost; this
    mirrors PipeModelDataParallelTopology's (pipe, data, model) rank order.
    ``ep`` (expert parallelism) sits just OUTSIDE data: expert factors out
    of the dp device set, so the all-to-all groups are dp-stride
    neighborhoods and a (expert, data)-sharded batch enumerates the same
    global order the plain dp mesh used.
    ``slices`` (multi-slice scale-out) is OUTERMOST: devices of one slice
    stay contiguous (they really share an ICI domain), dp factors within
    a slice, and only the `slice`-axis collectives cross DCN.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        denom = mp * pp * sp * ep * slices
        assert n % denom == 0, \
            f"{n} devices not divisible by mp*pp*sp*ep*slices={denom}"
        dp = n // denom
    sizes = {SLICE_AXIS: slices, PP_AXIS: pp, EP_AXIS: ep, DP_AXIS: dp,
             SP_AXIS: sp, MP_AXIS: mp}
    total = int(np.prod(list(sizes.values())))
    assert total == n, f"mesh {sizes} needs {total} devices, have {n}"
    shape = tuple(sizes[a] for a in axis_order)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_order)


class PipelineParallelGrid:
    """The "mpu" contract over a ProcessTopology (topology.py:252-455).

    Exposes rank/world-size accessors per axis. On TPU, "groups" are the
    named mesh axes themselves: ``get_*_parallel_group`` returns the axis
    name for use with shard_map collectives.
    """

    def __init__(self, topology: Optional[ProcessTopology] = None,
                 process_ranks: Optional[Sequence[int]] = None,
                 global_rank: int = 0):
        if topology is None:
            topology = PipeDataParallelTopology(1, 1)
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()
        coord = topology.get_coord(global_rank)
        self.data_parallel_size = max(1, topology.get_dim("data"))
        self.pipe_parallel_size = max(1, topology.get_dim("pipe"))
        self.model_parallel_size = max(1, topology.get_dim("model"))
        self.data_parallel_id = getattr(coord, "data", 0) if "data" in topology.axes else 0
        self.stage_id = getattr(coord, "pipe", 0) if "pipe" in topology.axes else 0
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in topology.axes else 0

        # Rank lists per axis (for checkpoint naming & debugging).
        self.dp_groups = topology.get_axis_comm_lists("data") if "data" in topology.axes else []
        self.pp_groups = topology.get_axis_comm_lists("pipe") if "pipe" in topology.axes else []
        self.mp_groups = topology.get_axis_comm_lists("model") if "model" in topology.axes else []

        # Pipeline adjacency (p2p.py:22-28 parity).
        self.stage_to_global = {}
        if "pipe" in topology.axes:
            kwargs = {a: getattr(coord, a) for a in topology.axes if a != "pipe"}
            for s in range(self.pipe_parallel_size):
                self.stage_to_global[s] = topology.get_rank(pipe=s, **kwargs)

    # --- topology ---
    @property
    def topology(self):
        return self._topo

    def get_global_rank(self) -> int:
        return self.global_rank

    # --- data parallel ---
    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_data_parallel_group(self) -> str:
        return DP_AXIS

    # --- model parallel ---
    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def get_model_parallel_group(self) -> str:
        return MP_AXIS

    # --- deprecated "slice parallel" alias -------------------------------
    # The reference's topology.py:445-455 spells MODEL (tensor-slicing)
    # parallelism "slice parallel". Since the multi-slice scale-out work
    # introduced a REAL `slice` mesh axis (SLICE_AXIS: ICI domains joined
    # by DCN — nothing to do with tensor slicing), that name is a footgun:
    # these shims keep the reference API alive but warn and delegate to
    # the model-parallel accessors, which are the real names.
    def _warn_slice_parallel_alias(self, name: str) -> None:
        import warnings
        warnings.warn(
            f"PipelineParallelGrid.{name}() is the reference's alias for "
            f"MODEL (tensor-slicing) parallelism — it is unrelated to the "
            f"'{SLICE_AXIS}' mesh axis (multi-slice DCN scale-out). Use "
            f"the get_model_parallel_* accessors.",
            DeprecationWarning, stacklevel=3)

    @property
    def slice_parallel_size(self) -> int:
        self._warn_slice_parallel_alias("slice_parallel_size")
        return self.model_parallel_size

    def get_slice_parallel_rank(self) -> int:
        self._warn_slice_parallel_alias("get_slice_parallel_rank")
        return self.model_parallel_id

    def get_slice_parallel_world_size(self) -> int:
        self._warn_slice_parallel_alias("get_slice_parallel_world_size")
        return self.model_parallel_size

    def get_slice_parallel_group(self) -> str:
        self._warn_slice_parallel_alias("get_slice_parallel_group")
        return MP_AXIS

    # --- pipeline ---
    def get_stage_id(self) -> int:
        return self.stage_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_pipe_parallel_group(self) -> str:
        return PP_AXIS

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global_rank(self, stage_id: int) -> int:
        return self.stage_to_global[stage_id]
