"""Wall-clock and throughput timers.

Parity with reference ``deepspeed/utils/timer.py``:
- ``SynchronizedWallClockTimer`` (timer.py:26-104): named timers whose
  start/stop fence outstanding device work. On TPU the fence is
  ``jax.block_until_ready`` / ``jax.effects_barrier`` rather than
  ``cuda.synchronize``; dispatch is async in the same way, so unfenced wall
  clocks under-report.
- ``ThroughputTimer`` (timer.py:106-183): samples/sec with warm-up steps.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from .logging import logger


# Instrumented fence counter: every _device_sync() is a full host↔device
# round trip, so tier-1 can ASSERT the no-added-hot-path-fences telemetry
# design rule instead of trusting it (tests/test_telemetry.py).
_SYNC_COUNT = 0


def device_sync_count() -> int:
    """Total _device_sync() fences issued by this process."""
    return _SYNC_COUNT


def _device_sync() -> None:
    """Block until all dispatched device work is complete."""
    global _SYNC_COUNT
    _SYNC_COUNT += 1
    try:
        import jax
        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timer group with device-synchronized boundaries."""

    class Timer:
        def __init__(self, name: str):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = 0.0
            self.count = 0

        def start(self, synchronize: bool = True) -> None:
            assert not self.started_, f"timer {self.name_} already started"
            if synchronize:
                _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset: bool = False, synchronize: bool = True) -> None:
            assert self.started_, f"timer {self.name_} not started"
            if synchronize:
                _device_sync()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.count += 1
            self.started_ = False

        def reset(self) -> None:
            self.elapsed_ = 0.0
            self.started_ = False
            self.count = 0

        def elapsed(self, reset: bool = True) -> float:
            started = self.started_
            count = self.count
            if started:
                self.stop(synchronize=False)
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                # Mid-run query: restore count so mean() reflects only real
                # start/stop cycles.
                self.count = count
                self.start(synchronize=False)
            return elapsed

        def mean(self) -> float:
            return self.elapsed_ / max(1, self.count)

    def __init__(self):
        self.timers: Dict[str, SynchronizedWallClockTimer.Timer] = {}

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None) -> str:
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        from .logging import log_dist
        log_dist(string, ranks=ranks or [0])
        return string


class ThroughputTimer:
    """Samples/sec tracker with warm-up, parity with timer.py:106-183.

    TPU-native delta: per-step device fences would serialize the async
    dispatch pipeline (each fence is a full host↔device round trip — ruinous
    on a tunneled backend), so by default the timer syncs only at reporting
    windows and averages over the window. ``synchronized=True`` restores the
    reference's fence-every-step behavior (wall_clock_breakdown).
    """

    def __init__(self, batch_size: int, num_workers: int = 1, start_step: int = 2,
                 steps_per_output: Optional[int] = None, monitor_memory: bool = False,
                 logging_fn=None, synchronized: bool = False):
        self.start_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.counted_steps = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.synchronized = synchronized
        # Windowed (non-synchronized) mode needs a window length to close
        # measurements; default to 100 steps when no report cadence is set.
        self._window_len = steps_per_output or 100
        self._window_start: Optional[float] = None
        self._window_steps = 0

    def update_epoch_count(self) -> None:
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self) -> None:
        self.started = True
        if self.global_step_count < self.start_step:
            return
        if self.synchronized:
            _device_sync()
            self.start_time = time.time()
        elif self._window_start is None:
            _device_sync()
            self._window_start = time.time()
            self._window_steps = 0

    def stop(self, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.global_step_count <= self.start_step:
            return
        if self.synchronized:
            _device_sync()
            duration = time.time() - self.start_time
            self.total_elapsed_time += duration
            self.counted_steps += 1
            self._maybe_report(report_speed, duration)
        else:
            self._window_steps += 1
            boundary = self.global_step_count % self._window_len == 0
            if boundary and self._window_start is not None:
                _device_sync()
                duration = time.time() - self._window_start
                self.total_elapsed_time += duration
                self.counted_steps += self._window_steps
                self._window_start = None
                self._maybe_report(report_speed,
                                   duration / max(1, self._window_steps))

    def _maybe_report(self, report_speed: bool, step_duration: float) -> None:
        if report_speed and self.steps_per_output and \
                self.global_step_count % self.steps_per_output == 0:
            self.logging(
                f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                f"global_step={self.global_step_count}, "
                f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.4f}, "
                f"CurrSamplesPerSec={self.batch_size * self.num_workers / max(step_duration, 1e-12):.4f}")

    def has_samples(self) -> bool:
        """True once at least one measurement window has closed — the
        explicit no-data signal (``avg_samples_per_sec`` returns 0.0
        before then; it used to return ``float("-1")``, a sentinel that
        read as a plausible-but-absurd rate downstream)."""
        return self.counted_steps > 0 and self.total_elapsed_time > 0

    def avg_samples_per_sec(self) -> float:
        if self.has_samples():
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / self.counted_steps
            return samples_per_step / max(avg_time_per_step, 1e-12)
        return 0.0
