"""Rank-aware logging.

Parity with reference ``deepspeed/utils/logging.py:7-60``: a singleton logger
plus ``log_dist(message, ranks=...)`` that only emits on the listed process
indices (``-1`` = all). On TPU the "rank" is ``jax.process_index()`` when the
distributed runtime is up, else 0.
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class _LoggerFactory:
    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            formatter = logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s")
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(formatter)
            logger_.addHandler(handler)
        return logger_


logger = _LoggerFactory.create_logger(
    level=LOG_LEVELS.get(os.environ.get("DS_TPU_LOG_LEVEL", "info"), logging.INFO))


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def should_log(ranks: Optional[Iterable[int]] = None) -> bool:
    """True when this process should emit for the given rank filter."""
    if ranks is None:
        ranks = [-1]
    ranks = list(ranks)
    if -1 in ranks:
        return True
    return _process_index() in ranks


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the processes listed in ``ranks``."""
    if should_log(ranks):
        logger.log(level, f"[Rank {_process_index()}] {message}")
