"""Environment report — ``ds_report`` equivalent (reference env_report.py:23).

Prints the software stack (jax/jaxlib/libtpu + friends), the accelerator
topology visible to this process, and per-op availability of the
deepspeed_tpu kernels/components (the analogue of the reference's
compiled/compatible op table).
"""
from __future__ import annotations

import importlib
import json
import os
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _version(mod_name: str) -> str:
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return "not installed"


def op_report(lines=None) -> list:
    """Availability of each optional component (op_builder table parity)."""
    out = lines if lines is not None else []
    checks = [
        ("flash_attention (pallas)", "deepspeed_tpu.ops.flash_attention"),
        ("sparse_attention", "deepspeed_tpu.ops.sparse_attention"),
        ("fused optimizers", "deepspeed_tpu.ops.optimizers"),
        ("onebit adam", "deepspeed_tpu.ops.onebit"),
        ("cpu adam (host offload)", "deepspeed_tpu.ops.cpu_adam"),
        ("transformer layer", "deepspeed_tpu.models.transformer"),
        ("pipeline engine", "deepspeed_tpu.runtime.pipe.engine"),
        ("flops profiler", "deepspeed_tpu.profiling.flops_profiler"),
        ("elasticity", "deepspeed_tpu.elasticity"),
    ]
    out.append("-" * 64)
    out.append(f"{'op / component':<36}{'status':>10}")
    out.append("-" * 64)
    for label, mod in checks:
        try:
            importlib.import_module(mod)
            status = GREEN_OK
        except Exception:
            status = RED_NO
        out.append(f"{label:<36}{status:>10}")
    return out


def device_report(lines=None) -> list:
    out = lines if lines is not None else []
    out.append("-" * 64)
    out.append("accelerator topology")
    out.append("-" * 64)
    try:
        import jax
        from .monitor.peaks import peaks_for_kind
        devs = jax.devices()
        out.append(f"platform ............... {devs[0].platform}")
        out.append(f"devices (global) ....... {jax.device_count()}")
        out.append(f"devices (local) ........ {jax.local_device_count()}")
        out.append(f"process count .......... {jax.process_count()}")
        for d in devs[: min(8, len(devs))]:
            kind = getattr(d, "device_kind", "?")
            # Per-chip ceilings from the shared peak table (the MFU /
            # roofline denominators — monitor/peaks.py).
            pk = peaks_for_kind(kind)
            peak = (f"no peak-table entry; roofline assumes {pk.name}"
                    if pk.assumed else
                    f"peak {pk.bf16_tflops:.0f} bf16 TFLOPs, "
                    f"{pk.hbm_gbs:.0f} GB/s HBM, {pk.ici_gbs:.0f} GB/s ICI, "
                    f"{pk.dcn_gbs:.3g} GB/s DCN")
            out.append(f"  device {d.id}: {kind} ({peak})")
        # The two interconnect tiers, side by side: multislice training
        # prices them separately (a step can be DCN-bound while ICI
        # idles — monitor/cost_model.py), so the operator should see the
        # ~30-60x gap here, not discover it in a slow step.
        pk0 = peaks_for_kind(getattr(devs[0], "device_kind", ""))
        flag = " (ASSUMED v5e row)" if pk0.assumed else ""
        out.append(
            f"interconnect tiers ..... ICI {pk0.ici_gbs:.0f} GB/s/chip | "
            f"DCN {pk0.dcn_gbs:.3g} GB/s/chip "
            f"({pk0.ici_gbs / pk0.dcn_gbs:.0f}x slower){flag}")
        # Resolved slice topology (DS_NUM_SLICES / multi-host env): how
        # the process world maps onto ICI domains.
        try:
            from .monitor.hostinfo import process_identity, slice_identity
            _, world = process_identity()
            slice_id, rank_in_slice, n_slices = slice_identity()
            out.append(
                f"slice topology ......... {n_slices} slice(s) x "
                f"{world // max(1, n_slices)} process(es)/slice"
                + (f"; this process: slice {slice_id} rank "
                   f"{rank_in_slice}" if n_slices > 1 else
                   " (single ICI domain)"))
        except Exception as e:
            out.append(f"slice topology ......... unresolved: {e}")
        try:
            stats = devs[0].memory_stats()
            if stats and "bytes_limit" in stats:
                out.append(f"hbm per chip ........... "
                           f"{stats['bytes_limit'] / 2**30:.1f} GiB")
        except Exception:
            pass
    except Exception as e:  # pragma: no cover
        out.append(f"jax devices unavailable: {e}")
    return out


def software_report(lines=None) -> list:
    out = lines if lines is not None else []
    out.append("-" * 64)
    out.append("software stack")
    out.append("-" * 64)
    out.append(f"python ................. {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy", "libtpu"):
        out.append(f"{mod:<24} {_version(mod)}")
    try:
        import deepspeed_tpu
        out.append(f"{'deepspeed_tpu':<24} "
                   f"{getattr(deepspeed_tpu, '__version__', 'dev')}")
    except Exception:
        pass
    return out


def find_lint_audit(path: str = None) -> str:
    """LINT_AUDIT.json location: explicit arg > $DS_LINT_AUDIT > cwd >
    the repo root this package sits in. An explicitly requested file
    that is missing is NOT silently replaced by a fallback — answering
    "did the compiled programs change under me" from a stale artifact
    is worse than answering not at all. Empty string when absent."""
    explicit = path or os.environ.get("DS_LINT_AUDIT")
    if explicit:
        return explicit if os.path.isfile(explicit) else ""
    candidates = [os.path.join(os.getcwd(), "LINT_AUDIT.json"),
                  os.path.join(os.path.dirname(
                      os.path.dirname(os.path.abspath(__file__))),
                      "LINT_AUDIT.json")]
    for c in candidates:
        if os.path.isfile(c):
            return c
    return ""


def lint_report(lines=None, path: str = None) -> list:
    """One-line static-lint summary when a LINT_AUDIT.json is present
    (tools/ds_lint.py over the flagship configs): configs passed, waived
    count, and the newest finding — the operator's 10-second answer to
    "did the compiled programs change under me"."""
    out = lines if lines is not None else []
    fp = find_lint_audit(path)
    if not fp:
        explicit = path or os.environ.get("DS_LINT_AUDIT")
        if explicit:
            out.append(f"static lint: requested audit missing: {explicit}")
        return out
    try:
        with open(fp) as f:
            rec = json.load(f)
        configs = rec.get("configs", {})
        passed = sum(1 for c in configs.values() if c.get("pass"))
        findings = [f for c in configs.values()
                    for f in c.get("findings", [])]
        unwaived = sum(len(c.get("unwaived", [])) for c in configs.values())
        waived = len(rec.get("waived", []))
        newest = findings[-1]["fingerprint"] if findings else "none"
        status = GREEN_OK if rec.get("all_pass") else RED_NO
        out.append("-" * 64)
        out.append(
            f"static lint {status} {passed}/{len(configs)} configs pass, "
            f"{len(findings)} finding(s) ({waived} waived, "
            f"{unwaived} unwaived); newest: {newest}")
    except Exception as e:  # a corrupt artifact must not kill ds_report
        out.append(f"static lint: unreadable {fp}: {e}")
    return out


def main() -> int:
    lines: list = []
    lines.append("=" * 64)
    lines.append("deepspeed_tpu environment report (ds_report)")
    lines.append("=" * 64)
    software_report(lines)
    device_report(lines)
    op_report(lines)
    lint_report(lines)
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
