#!/usr/bin/env python
"""Multi-slice gradient-sync ablation: flat vs hierarchical vs
hierarchical + 1-bit DCN compression, on the two-tier analytic model.

Projects per-step gradient-sync communication for a production-shaped
config (gpt2-large on 2 x 64-chip v5e slices by default) under the
three schedules:

- **flat**: one joint collective over (slice, data) — every link,
  including the DCN boundary links, carries ~grad-sized traffic;
- **hierarchical**: in-slice reduce-scatter over ICI, inter-slice
  all-reduce of the 1/dp residual over DCN (parallel/multislice.py) —
  the DCN traffic divides by dp;
- **hierarchical + 1-bit DCN**: the same schedule with the inter-slice
  hop in the packed sign-bit wire format
  (``zero_optimization.dcn_compression``) — ~32x fewer DCN bytes again.

Times are PROJECTIONS from the analytic wire model and the shared chip
peak table (monitor/peaks.py) — per-chip ICI vs DCN bandwidth — NOT
measurements: this box has no TPU and no DCN, and the CPU "slices" the
tests run on are virtual mesh axes in one host's memory. What the
projection is for is the STRUCTURAL claim (how many bytes cross the
slow tier per step under each schedule), which tools/comm_audit.py pins
against the compiled programs, and the MULTISLICE_BENCH.json record
tools/bench_gate.py gates DCN-byte rises with.

Usage: python ablate_multislice.py [--record] [--slices 2] [--dp 64]
                                   [--model gpt2-large]
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init  # noqa: E402
from deepspeed_tpu.monitor.peaks import peaks_for_kind  # noqa: E402
from deepspeed_tpu.parallel import hlo_audit  # noqa: E402
from deepspeed_tpu.parallel.multislice import (  # noqa: E402
    dcn_compression_ratio)


def project(model_name: str, slices: int, dp: int, chip: str = "v5e"):
    cfg = GPT2_CONFIGS[model_name]
    # Shapes only — eval_shape traces init without touching a device.
    shapes = jax.eval_shape(
        lambda k: gpt2_init(k, cfg), jax.random.PRNGKey(0))
    n_el = sum(int(jnp.prod(jnp.asarray(l.shape)))
               for l in jax.tree_util.tree_leaves(shapes))
    model = hlo_audit.grad_sync_wire_model(shapes, dp, slices=slices)
    peaks = peaks_for_kind(chip)

    def ms(nbytes: float, bw_bytes_per_s: float) -> float:
        return nbytes / bw_bytes_per_s * 1e3

    flat_dcn = model["flat_dcn_link_bytes"]
    rows = {
        "flat": {
            "ici_bytes_per_step": model["reduce_scatter_wire_bytes"],
            "dcn_bytes_per_step": int(flat_dcn),
            "note": "joint (slice, data) ring: ~grad-sized traffic on "
                    "every link incl. the DCN boundary links",
        },
        "hierarchical": {
            "ici_bytes_per_step": model["ici_wire_bytes"],
            "dcn_bytes_per_step": model["dcn_wire_bytes"],
            "note": "in-slice reduce-scatter + inter-slice all-reduce "
                    "of the 1/dp residual",
        },
        "hierarchical_1bit_dcn": {
            "ici_bytes_per_step": model["ici_wire_bytes"],
            "dcn_bytes_per_step": model["dcn_wire_bytes_compressed"],
            "note": "same schedule; DCN hop in the packed sign-bit "
                    "wire format (zero_optimization.dcn_compression)",
        },
    }
    for row in rows.values():
        t_ici = ms(row["ici_bytes_per_step"], peaks.ici_bytes_per_sec)
        t_dcn = ms(row["dcn_bytes_per_step"], peaks.dcn_bytes_per_sec)
        row.update(projected_t_ici_ms=round(t_ici, 4),
                   projected_t_dcn_ms=round(t_dcn, 4),
                   projected_comm_floor_ms=round(max(t_ici, t_dcn), 4),
                   comm_bound_tier="dcn" if t_dcn > t_ici else "ici")
    return {
        "model": model_name,
        "param_elements": int(n_el),
        "slices": slices,
        "dp_per_slice": dp,
        "chip": peaks.as_dict(),
        "wire_model": {k: v for k, v in model.items() if k != "moe"},
        "schedules": rows,
        "dcn_compression_ratio_flagship": round(
            dcn_compression_ratio(1 << 20, slices), 2),
    }


def project_zero3(model_name: str, slices: int, dp: int,
                  chip: str = "v5e"):
    """ISSUE 18 headline figure: stage-3 across slices, flat vs
    hierarchical analytic walls. Under the flat lowering the param
    all-gathers bind the JOINT (slice, data) group — 2 gathers/step of
    compute-dtype param bytes ride every link including the DCN
    boundary. The axis-algebra planner binds them to `data` instead:
    all gather traffic stays on ICI and the DCN hop is the same 1/dp
    f32 residual stage 2 ships — zero param bytes on the slow tier."""
    cfg = GPT2_CONFIGS[model_name]
    shapes = jax.eval_shape(
        lambda k: gpt2_init(k, cfg), jax.random.PRNGKey(0))
    pbytes = jnp.dtype(cfg.dtype).itemsize
    model = hlo_audit.grad_sync_wire_model(
        shapes, dp, slices=slices, zero3=True,
        param_bytes_per_el=pbytes)
    peaks = peaks_for_kind(chip)

    def ms(nbytes: float, bw_bytes_per_s: float) -> float:
        return nbytes / bw_bytes_per_s * 1e3

    rows = {
        "flat": {
            "ici_bytes_per_step": model["ici_wire_bytes"],
            "dcn_bytes_per_step": int(model["flat_dcn_link_bytes"]),
            "dcn_param_bytes": 2 * model["param_gather_payload_bytes"],
            "note": "joint (slice, data) gathers + scatter: both "
                    "compute-dtype param gathers cross the DCN "
                    "boundary links every micro-step",
        },
        "hierarchical": {
            "ici_bytes_per_step": model["ici_wire_bytes"],
            "dcn_bytes_per_step": model["dcn_wire_bytes"],
            "dcn_param_bytes": model["dcn_param_bytes"],
            "note": "planner-derived: gathers bind `data` (ICI only); "
                    "DCN carries the 1/dp f32 residual once per step",
        },
    }
    for row in rows.values():
        t_ici = ms(row["ici_bytes_per_step"], peaks.ici_bytes_per_sec)
        t_dcn = ms(row["dcn_bytes_per_step"], peaks.dcn_bytes_per_sec)
        row.update(projected_t_ici_ms=round(t_ici, 4),
                   projected_t_dcn_ms=round(t_dcn, 4),
                   projected_comm_floor_ms=round(max(t_ici, t_dcn), 4),
                   comm_bound_tier="dcn" if t_dcn > t_ici else "ici")
    return {
        "model": model_name,
        "slices": slices,
        "dp_per_slice": dp,
        "param_bytes_per_el": int(pbytes),
        "chip": peaks.as_dict(),
        "wire_model": {k: v for k, v in model.items() if k != "moe"},
        "schedules": rows,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--record", action="store_true",
                    help="write MULTISLICE_BENCH.json")
    ap.add_argument("--zero3", action="store_true",
                    help="add the stage-3-across-slices section (flat "
                         "joint-axis gathers vs planner-derived "
                         "ICI-only gathers)")
    ap.add_argument("--slices", type=int, default=2)
    ap.add_argument("--dp", type=int, default=64,
                    help="dp degree WITHIN one slice (default 64 — one "
                         "v5e-64 slice)")
    ap.add_argument("--model", default="gpt2-large")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "MULTISLICE_BENCH.json"))
    args = ap.parse_args()

    proj = project(args.model, args.slices, args.dp)
    h = proj["schedules"]["hierarchical"]
    hc = proj["schedules"]["hierarchical_1bit_dcn"]
    f = proj["schedules"]["flat"]
    rec = {
        "generated_by": "ablate_multislice.py",
        "methodology": (
            "ANALYTIC PROJECTION on the two-tier ring wire model + the "
            "shared chip peak table — no TPU and no DCN on this box; "
            "the CPU-mesh 'slices' the tests audit are virtual axes in "
            "one host. The structural byte counts are compiled-program "
            "truth (tools/comm_audit.py multislice flagship); the "
            "times are model arithmetic, to be re-recorded measured on "
            "a real multislice pod."),
        "projection": proj,
        # The record bench_gate diffs across rounds: hierarchical
        # (active-schedule) DCN bytes/step — a rise means something
        # started shipping more over the slow tier.
        "multislice": {
            "available": True,
            "dcn_bytes_per_step": h["dcn_bytes_per_step"],
            "dcn_bytes_per_step_compressed": hc["dcn_bytes_per_step"],
            "flat_dcn_bytes_per_step": f["dcn_bytes_per_step"],
            "ici_bytes_per_step": h["ici_bytes_per_step"],
            "dcn_reduction_vs_flat": round(
                f["dcn_bytes_per_step"] / max(1, h["dcn_bytes_per_step"]),
                2),
            "dcn_reduction_compressed_vs_dense": round(
                h["dcn_bytes_per_step"] /
                max(1, hc["dcn_bytes_per_step"]), 2),
        },
    }
    if args.zero3:
        z3 = project_zero3(args.model, args.slices, args.dp)
        zf = z3["schedules"]["flat"]
        zh = z3["schedules"]["hierarchical"]
        rec["projection_zero3"] = z3
        # The gated stage-3 figures: the planner's schedule must keep
        # ZERO param bytes on DCN; the flat joint-axis link bytes are
        # the wall it avoids.
        rec["zero3"] = {
            "available": True,
            "dcn_bytes_per_step": zh["dcn_bytes_per_step"],
            "dcn_param_bytes_per_step": zh["dcn_param_bytes"],
            "flat_dcn_link_bytes_per_step": zf["dcn_bytes_per_step"],
            "ici_wire_bytes_per_step": zh["ici_bytes_per_step"],
            "dcn_reduction_vs_flat": round(
                zf["dcn_bytes_per_step"] /
                max(1, zh["dcn_bytes_per_step"]), 2),
        }
    print(json.dumps({k: rec["multislice"][k] for k in
                      ("dcn_bytes_per_step",
                       "dcn_bytes_per_step_compressed",
                       "flat_dcn_bytes_per_step",
                       "dcn_reduction_vs_flat",
                       "dcn_reduction_compressed_vs_dense")}, indent=1))
    for name, row in proj["schedules"].items():
        print(f"[{name}] ici {row['ici_bytes_per_step']:,} B | dcn "
              f"{row['dcn_bytes_per_step']:,} B | floor "
              f"{row['projected_comm_floor_ms']} ms "
              f"({row['comm_bound_tier']}-bound)")
    if args.zero3:
        for name, row in rec["projection_zero3"]["schedules"].items():
            print(f"[zero3/{name}] ici {row['ici_bytes_per_step']:,} B "
                  f"| dcn {row['dcn_bytes_per_step']:,} B (param "
                  f"{row['dcn_param_bytes']:,} B) | floor "
                  f"{row['projected_comm_floor_ms']} ms "
                  f"({row['comm_bound_tier']}-bound)")
    if args.record:
        with open(args.out, "w") as fobj:
            json.dump(rec, fobj, indent=1)
        print(f"[ablate_multislice] wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
