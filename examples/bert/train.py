"""BERT masked-LM pretraining example (BASELINE.json configs[1] shape:
BERT pretraining with ZeRO-1 + fused Adam). Synthetic MLM batches; plug a
real corpus by replacing ``synthetic_mlm``.

    python examples/bert/train.py --steps 50 [--model bert-tiny]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import numpy as np

# Honor JAX_PLATFORMS from the environment: the TPU-harness sitecustomize
# force-sets the platform at startup, so the env var alone is ignored —
# required for running these scripts on the virtual CPU mesh (CI).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import deepspeed_tpu
from deepspeed_tpu.models.bert import (BERT_CONFIGS, bert_init,
                                       bert_mlm_loss_fn)


def synthetic_mlm(n, cfg, mask_prob=0.15, seed=0):
    rng = np.random.default_rng(seed)
    S = cfg.max_seq_length
    tokens = rng.integers(4, cfg.vocab_size, size=(n, S)).astype(np.int32)
    labels = np.full((n, S), -100, np.int32)
    mask = rng.random((n, S)) < mask_prob
    labels[mask] = tokens[mask]
    tokens = tokens.copy()
    tokens[mask] = 3          # [MASK]
    return tokens, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--model", default="bert-tiny",
                    choices=sorted(BERT_CONFIGS))
    args = ap.parse_args()

    cfg = BERT_CONFIGS[args.model]
    ds_config = {
        "train_batch_size": 8,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
        "steps_per_print": 10,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=bert_mlm_loss_fn(cfg),
        model_params=bert_init(jax.random.PRNGKey(0), cfg),
        config=ds_config)

    tokens, labels = synthetic_mlm(8 * 16, cfg)
    losses = []
    for step in range(args.steps):
        lo = (step * 8) % (len(tokens) - 8)
        loss = engine.train_batch((tokens[lo:lo + 8], labels[lo:lo + 8]))
        losses.append(float(jax.device_get(loss)))
    # stdout contract consumed by tests/test_examples.py: the full curve
    # (decreasing-loss check) and the final value.
    print("losses:", " ".join(f"{l:.6f}" for l in losses))
    print(f"final MLM loss: {losses[-1]:.4f}")


if __name__ == "__main__":
    main()
