"""GPT-2 training example — the Megatron_GPT2 config-matrix analogue.

Pick a ds_config from this directory (ZeRO-2, ZeRO-Offload, 1-bit Adam,
pipeline) or pass your own. Data defaults to synthetic token streams
(no egress); pass real data via --data: an .npy file of int32 [N, S+1]
token windows, or a .txt file (e.g. the vendored
examples/data/corpus.txt) which is byte-level tokenized into next-byte
prediction windows.

    python examples/gpt2/train.py --config ds_config_zero2.json --steps 50
    python examples/gpt2/train.py --config ds_config_offload.json
    python examples/gpt2/train.py --config ds_config_onebit.json
    python examples/gpt2/train.py --config ds_config_pipeline.json --pipeline
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import numpy as np

# Honor JAX_PLATFORMS from the environment: the TPU-harness sitecustomize
# force-sets the platform at startup, so the env var alone is ignored —
# required for running these scripts on the virtual CPU mesh (CI).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import deepspeed_tpu
from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init, gpt2_loss_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="ds_config_zero2.json")
    ap.add_argument("--model", default="gpt2-tiny",
                    choices=sorted(GPT2_CONFIGS))
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--pipeline", action="store_true")
    ap.add_argument("--data", default=None,
                    help="npy int32 [N, S+1], or a .txt file "
                         "(byte-level tokenized)")
    ap.add_argument("--checkpoint_dir", default=None)
    args = ap.parse_args()

    here = os.path.dirname(os.path.abspath(__file__))
    cfg_path = args.config if os.path.isabs(args.config) \
        else os.path.join(here, args.config)
    with open(cfg_path) as f:
        ds_config = json.load(f)

    cfg = GPT2_CONFIGS[args.model]
    if args.pipeline:
        from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
        model = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            config=ds_config, model=model)
    else:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg),
            model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
            config=ds_config)

    bs = ds_config["train_batch_size"]
    S = cfg.max_seq_length
    if args.data and args.data.endswith(".txt"):
        # Byte-level LM on real text: every UTF-8 byte is a token
        # (vocab 256 fits every config), windowed into [N, S+1] rows of
        # next-byte prediction. The reference for "the examples train
        # on REAL data", closing VERDICT.md's synthetic-tokens gap.
        raw = np.frombuffer(open(args.data, "rb").read(), dtype=np.uint8)
        n_rows = len(raw) // (S + 1)
        assert n_rows >= bs, f"corpus too small: {len(raw)} bytes"
        tokens = raw[:n_rows * (S + 1)].reshape(n_rows, S + 1) \
            .astype(np.int32)
        rng = np.random.default_rng(0)
        tokens = tokens[rng.permutation(n_rows)]
    elif args.data:
        tokens = np.load(args.data).astype(np.int32)
    else:
        # Markov synthetic stream: the next token is a fixed affine map of
        # the current one 90% of the time. A uniform random stream would
        # already sit AT the ln(V) optimum from init — unlearnable by
        # construction — while this has real next-token structure, so the
        # loss visibly decreases within a few dozen steps (the contract
        # tests/test_examples.py checks).
        rng = np.random.default_rng(0)
        n = bs * 16
        cols = [rng.integers(0, cfg.vocab_size, size=(n, 1))]
        resample = rng.random((n, S)) < 0.1
        rand = rng.integers(0, cfg.vocab_size, size=(n, S))
        for t in range(S):
            nxt = (cols[-1] * 7 + 1) % cfg.vocab_size
            cols.append(np.where(resample[:, t:t + 1],
                                 rand[:, t:t + 1], nxt))
        tokens = np.concatenate(cols, axis=1).astype(np.int32)

    assert len(tokens) >= bs, \
        f"need >= {bs} rows (train_batch_size), got {len(tokens)}"
    n_windows = max(1, len(tokens) - bs + 1)
    losses = []
    for step in range(args.steps):
        lo = (step * bs) % n_windows
        loss = engine.train_batch(tokens[lo:lo + bs])
        losses.append(float(jax.device_get(loss)))
    # stdout contract consumed by tests/test_examples.py: the full curve
    # (decreasing-loss check) and the final value.
    print("losses:", " ".join(f"{l:.6f}" for l in losses))
    print(f"final loss: {losses[-1]:.4f}")
    if args.checkpoint_dir:
        engine.save_checkpoint(args.checkpoint_dir)


if __name__ == "__main__":
    main()
