"""CIFAR-10-scale training example (BASELINE.json configs[0]).

Mirrors DeepSpeedExamples/cifar: a small conv net driven entirely by the
ds_config JSON. Data is synthetic CIFAR-shaped (this environment has no
egress); swap ``synthetic_cifar`` for a real loader to train for real.

    python examples/cifar/train.py --steps 200 [--deepspeed_config ds_config.json]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

# Honor JAX_PLATFORMS from the environment: the TPU-harness sitecustomize
# force-sets the platform at startup, so the env var alone is ignored —
# required for running these scripts on the virtual CPU mesh (CI).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import deepspeed_tpu


def net_apply(params, x):
    """3x conv (as grouped matmuls over patches) -> pooled linear head."""
    B = x.shape[0]
    h = x.reshape(B, 8, 4, 8, 4, 3).transpose(0, 1, 3, 2, 4, 5)
    h = h.reshape(B, 64, 48)                      # 4x4 patches
    h = jnp.tanh(h @ params["w1"] + params["b1"])
    h = jnp.tanh(h @ params["w2"] + params["b2"])
    h = h.mean(axis=1)                            # global average pool
    return h @ params["w3"] + params["b3"]


def init_params(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    s = 0.1
    return {
        "w1": jax.random.normal(k1, (48, 128)) * s, "b1": jnp.zeros((128,)),
        "w2": jax.random.normal(k2, (128, 128)) * s, "b2": jnp.zeros((128,)),
        "w3": jax.random.normal(k3, (128, 10)) * s, "b3": jnp.zeros((10,)),
    }


def loss_fn(params, batch, rng):
    x, y = batch
    logits = net_apply(params, x)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.sum(jax.nn.one_hot(y, 10) * logp, axis=-1))


def synthetic_cifar(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    # learnable labels: class = sign pattern of channel means
    y = ((x.mean(axis=(1, 2)) > 0) * np.array([1, 2, 4])).sum(-1) % 10
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--deepspeed_config", default=None)
    args = ap.parse_args()

    config = args.deepspeed_config or {
        "train_batch_size": 64,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 20}},
        "steps_per_print": 20,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_params=init_params(jax.random.PRNGKey(0)),
        config=config)
    x, y = synthetic_cifar(64 * 8)
    losses = []
    for step in range(args.steps):
        lo = (step * 64) % (64 * 8)
        loss = engine.train_batch((x[lo:lo + 64], y[lo:lo + 64]))
        losses.append(float(jax.device_get(loss)))
    # stdout contract consumed by tests/test_examples.py: the full curve
    # (decreasing-loss check) and the final value.
    print("losses:", " ".join(f"{l:.6f}" for l in losses))
    print(f"final loss: {losses[-1]:.4f}")


if __name__ == "__main__":
    main()
