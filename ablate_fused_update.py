"""Perf ablation: fused Pallas optimizer apply vs the optax chain (dev
tool, not shipped API).

Times ONLY the optimizer apply (grads fixed, full train step excluded) for
a GPT-2-shaped param tree, across:

    optax           — optax.adamw update + apply_updates (XLA's own fusion)
    fused           — Pallas multi-tensor chunked apply (ops/fused_update)
    fused_per_leaf  — same kernel, one launch per leaf (no chunking)

and, under --sr, the master-free bf16 variants (stochastic-rounding write).

Timing is the two-point scan-slope method from profile_matmul_bound.py:
per-op cost = (t(scan N) - t(scan 1)) / (N - 1), so the tunnel's ~100 ms
per-call round-trip cancels.

Also prints the roofline: minimum HBM bytes an apply must move per param
element (read g+p+m+v, write p+m+v), the bytes each variant actually
moves (the chunked front end adds flatten/unflatten passes over g and p),
and the implied HBM bandwidth — if the fused apply's achieved GB/s sits
at the chip's HBM ceiling, the optimizer step is provably
bandwidth-bound and no further kernel work can buy more
(the acceptance alternative in ISSUE.md).

Usage: python ablate_fused_update.py [model] [--sr]
"""
import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init
from deepspeed_tpu.ops.fused_update import fused_adam

ARGS = [a for a in sys.argv[1:] if not a.startswith("--")]
SR = "--sr" in sys.argv
MODEL = ARGS[0] if ARGS else (
    "gpt2-large" if jax.devices()[0].platform == "tpu" else "gpt2-tiny")
N = 32 if jax.devices()[0].platform == "tpu" else 4

# v5e HBM ~819 GB/s (public figure); used only for the roofline fraction.
HBM_GBS = {"v5e": 819.0, "v4": 1228.0, "v5p": 2765.0, "v6e": 1640.0}


def chip_hbm_gbs() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for k, v in HBM_GBS.items():
        if k in kind:
            return v
    return 819.0


def timed_apply(apply_fn, grads, params, opt_state) -> float:
    """ms per apply via the two-point scan slope (see module docstring)."""
    def make(length):
        @jax.jit
        def many(g, p, s):
            def body(carry, _):
                p, s = carry
                return apply_fn(g, p, s), None
            (p, s), _ = jax.lax.scan(body, (p, s), None, length=length)
            return p, s
        return many

    def run(length):
        fn = make(length)
        out = fn(grads, params, opt_state)       # compile + warm
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = fn(grads, params, opt_state)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) * 1e3

    t_n, t_1 = run(N), run(1)
    return max(0.0, (t_n - t_1) / (N - 1))


def main():
    cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=256)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    pdtype = jnp.bfloat16 if SR else jnp.float32
    params = jax.tree_util.tree_map(
        lambda x: x.astype(pdtype) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, params)
    grads = jax.tree_util.tree_map(
        lambda x: (jax.random.normal(jax.random.PRNGKey(1), x.shape,
                                     jnp.float32) * 1e-3).astype(x.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    n_leaves = len([l for l in jax.tree_util.tree_leaves(params)
                    if jnp.issubdtype(l.dtype, jnp.floating)])
    n_elems = sum(int(np.prod(l.shape))
                  for l in jax.tree_util.tree_leaves(params)
                  if jnp.issubdtype(l.dtype, jnp.floating))
    psize = 2 if SR else 4
    # Grads are f32 end-to-end: the engine promotes them at birth (f32
    # accumulation / second-moment precision) and the fused front end
    # flattens them in f32.
    gsize = 4
    # One apply must at minimum read g+p+m+v and write p+m+v (m/v f32).
    min_bytes = n_elems * (gsize + psize + 4 + 4 + psize + 4 + 4)
    # The chunked front end adds flatten (read+write g and p) and
    # unflatten (read+write p) passes.
    chunk_bytes = min_bytes + n_elems * (2 * gsize + 3 * psize)

    sched = lambda c: jnp.asarray(1e-4, jnp.float32)
    key = jax.random.PRNGKey(7)
    variants = {}

    tx = optax.adamw(sched, weight_decay=0.01)

    def optax_apply(g, p, s):
        u, s = tx.update(g, s, p)
        if SR:
            from deepspeed_tpu.ops.stochastic_rounding import \
                tree_stochastic_round_bf16
            summed = jax.tree_util.tree_map(
                lambda p_, u_: p_.astype(jnp.float32) + u_, p, u)
            return tree_stochastic_round_bf16(summed, key), s
        return optax.apply_updates(p, u), s

    # Master-free: moments must init f32 even from bf16 params.
    opt_init = (lambda p: tx.init(jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32), p))) if SR else tx.init
    variants["optax"] = (optax_apply, opt_init(params))

    for name, mt in (("fused", True), ("fused_per_leaf", False)):
        ftx = fused_adam(sched, weight_decay=0.01, multi_tensor=mt)

        def fused_apply(g, p, s, _ftx=ftx):
            new_p, new_s = _ftx.fused_apply(
                g, s, p, sr_key=key if SR else None)
            return new_p, new_s
        variants[name] = (fused_apply, ftx.init(params))

    results = {}
    for name, (fn, st) in variants.items():
        ms = timed_apply(fn, grads, params, st)
        results[name] = round(ms, 3)

    fused_ms = results["fused"]
    rec = {
        "model": f"{MODEL} ({n_elems/1e6:.1f}M params, {n_leaves} leaves)",
        "mode": "master-free bf16 + SR" if SR else "fp32 params",
        "ms_per_apply": results,
        "per_leaf_vs_chunked": round(
            results["fused_per_leaf"] / max(fused_ms, 1e-9), 2),
        "optax_vs_fused": round(results["optax"] / max(fused_ms, 1e-9), 2),
        "roofline": {
            "min_bytes_per_apply": min_bytes,
            "chunked_front_end_bytes": chunk_bytes,
            "fused_achieved_gb_s": round(
                chunk_bytes / max(fused_ms, 1e-9) / 1e6, 1),
            "hbm_peak_gb_s": chip_hbm_gbs(),
            "hbm_bound_fraction": round(
                chunk_bytes / max(fused_ms, 1e-9) / 1e6 / chip_hbm_gbs(),
                3),
        },
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    sys.exit(main())
