"""Benchmark: GPT-2 training throughput on the available hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline metric is model FLOPs utilisation-bearing throughput —
tokens/sec and TFLOPs/chip on a GPT-2 training step (ZeRO-2 + bf16), the
reference's own yardstick (SURVEY §6: DeepSpeed reports 64 TFLOPs/V100 ≈ 50%
of peak on its fused BERT kernels; `vs_baseline` is our achieved fraction of
peak vs their 0.50 fraction of peak).
"""
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def pick_model():
    """Size the benchmark model to the hardware: real TPU gets a big config,
    CPU fallback (dev runs) gets tiny."""
    platform = jax.devices()[0].platform
    from deepspeed_tpu.models import GPT2_CONFIGS
    if platform == "tpu":
        # GPT-2 large: the largest ladder config whose full fp32 Adam state
        # fits one chip's HBM (gpt2-xl at 1.5B needs 18.7 GB of optimizer
        # state alone — the reference pairs 1.5B with ZeRO-Offload for the
        # same reason, BASELINE.json configs[3]). Unrolled layers + chunked
        # CE head are the perf-tuned settings (see ablate.py history).
        return dataclasses.replace(
            GPT2_CONFIGS["gpt2-large"], max_seq_length=1024,
            # Round-5 default: NO remat + master-free bf16 (DS_BENCH_SR).
            # Stochastic rounding drops the fp32 masters AND the cast
            # cache — exactly the HBM that lets remat=none fit at mbs=4 —
            # and cuts optimizer traffic: 103.4 (dots_flash+masters) ->
            # 108.1 TFLOPs on v5e. Each alone is ~noise (103.6/103.8);
            # the memory synergy is the win. dots_flash remains the
            # fp32-master setting (DS_BENCH_SR=0 flips remat back too).
            remat_policy=os.environ.get(
                "DS_BENCH_REMAT",
                "none" if os.environ.get("DS_BENCH_SR", "1") == "1"
                else "dots_flash"),
            hidden_dropout=0.0, attn_dropout=0.0,
            scan_layers=False), int(os.environ.get("DS_BENCH_MBS", "4"))
    return dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], hidden_dropout=0.0, attn_dropout=0.0), 4


# The chip peak table lives in monitor/peaks.py now — ONE source of
# truth shared with the roofline cost model, env_report, and the bench
# gate. Re-exported here for the historical bench API (unknown kinds,
# incl. CPU dev runs, report vs an assumed v5e peak as before).
from deepspeed_tpu.monitor.peaks import (TPU_PEAK_TFLOPS,   # noqa: F401
                                         chip_peak_tflops)


def bench_offload_xl(gas: int = 1, n_steps: int = 2,
                     overlap: bool = None, host_threads: int = None,
                     bucket_mb: int = None):
    """North-star config (BASELINE.json): GPT-2 1.5B on ONE chip via
    ZeRO-Offload — full fp32 Adam state (17 GB) in host RAM, C++ SIMD Adam,
    bf16 grads D2H / params H2D each step. The reference's flagship
    ZeRO-Offload claim is exactly this shape of run (13B-on-one-V100,
    docs/_posts/2020-09-09-ZeRO-Offload.md:10).

    ``overlap`` (default env DS_BENCH_OFFLOAD_OVERLAP, on) selects the
    bucketed overlapped pipeline; False reproduces the serial numbers.
    ``host_threads``/``bucket_mb`` map to the zero_optimization knobs.

    NOT run inside the default bench: on this dev harness the chip is
    reached through a tunnel whose D2H path measures ~0.03 GB/s (H2D ~1
    GB/s), so each offload step pays minutes shipping grads host-ward —
    an environment artifact, not a design cost. ``tools/offload_bench.py``
    runs this once and records OFFLOAD_BENCH.json, which main() attaches
    to the headline line; DS_BENCH_OFFLOAD=1 forces a live run instead."""
    import dataclasses
    from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh

    if overlap is None:
        overlap = os.environ.get("DS_BENCH_OFFLOAD_OVERLAP", "1") == "1"
    if host_threads is None:
        host_threads = int(os.environ.get("DS_BENCH_OFFLOAD_THREADS", "0"))
    if bucket_mb is None:
        bucket_mb = int(os.environ.get("DS_BENCH_OFFLOAD_BUCKET_MB", "64"))
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-xl"], max_seq_length=1024,
        remat_policy="dots", hidden_dropout=0.0, attn_dropout=0.0,
        # scan_layers: one compiled block (a 48-layer unroll at 1.5B
        # overwhelms the AOT compiler); offload throughput is transfer-
        # dominated regardless.
        scan_layers=True)
    micro_bs = 4
    # One-chip bench by definition (the flagship claim is big-model-on-ONE-
    # device); a full-host mesh would also break the batch triple at dp>1.
    mesh = build_mesh(devices=jax.devices()[:1])
    # Init the masters host-side: the offload engine keeps fp32 state in
    # host RAM anyway, and a device init would pay 6 GB of slow D2H.
    with jax.default_device(jax.devices("cpu")[0]):
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    ds_config = {
        "train_batch_size": micro_bs * gas,
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "overlap_comm": overlap,
                              "offload_bucket_size": bucket_mb * 2 ** 20,
                              "offload_host_threads": host_threads},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 10 ** 9,
    }
    engine = DeepSpeedEngine(model=gpt2_loss_fn(cfg), model_params=params,
                             config=ds_config, mesh=mesh)
    del params
    S = cfg.max_seq_length
    batch = jnp.asarray(np.random.randint(
        0, cfg.vocab_size, size=(micro_bs * gas, S + 1), dtype=np.int32))
    engine.train_batch(batch)      # compile + first host step
    t0 = time.perf_counter()
    for _ in range(n_steps):
        engine.train_batch(batch)  # offload steps are host-synchronous
    dt = (time.perf_counter() - t0) / n_steps
    tokens_per_sec = micro_bs * gas * S / dt
    tflops = tokens_per_sec * gpt2_flops_per_token(cfg, S) / 1e12
    t = dict(engine.offload_timings or {})
    # Scalar phase components only (the per-bucket lists and pipeline
    # metadata ride alongside, not in the reconciliation sum).
    comp = {k: v for k, v in t.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
            and k.endswith("_ms") and k not in
            ("wall_ms", "pipeline_span_ms", "pipeline_work_ms",
             "d2h_reshard_ms")}   # reshard is already folded into d2h_ms
    comp_sum_ms = sum(comp.values())

    # Device-only step: params are resident and no H2D is pending after the
    # timed loop, so a bare grads pass fenced by the loss fetch is pure
    # compute — the number the round-4 record could not support.
    micro = engine._stack_micro_batches(batch)
    # Fence the last step's async param upload — without this the grad
    # pass blocks on the in-flight H2D and "device only" absorbs it.
    jax.block_until_ready(engine.state.params)
    t_dev = time.perf_counter()
    _, loss = engine._offload_grad_fn(
        engine.state.params, micro, engine._base_rng,
        jnp.asarray(engine.global_steps, jnp.int32),
        jnp.asarray(engine._offload.loss_scale, jnp.float32))
    _ = float(jax.device_get(loss))
    device_only_ms = (time.perf_counter() - t_dev) * 1e3

    # Transfer byte accounting (what the tunnel moves each step): bf16
    # grads down, bf16 params up.
    grad_bytes = sum(int(np.prod(l.shape)) * 2 for l in
                     jax.tree_util.tree_leaves(engine.state.params))
    # Projection to a real TPU-VM host (local PCIe/DMA, not the dev
    # tunnel): same measured device compute + host Adam, transfers at the
    # stated bandwidth. TPU-VM hosts measure >10 GB/s; 10 is conservative.
    vm_gbs = 10.0
    xfer_ms = 2 * grad_bytes / (vm_gbs * 1e9) * 1e3      # D2H + H2D
    host_work_ms = t.get("host_step_ms", 0.0) + t.get("host_norm_ms", 0.0)
    serial_ms = device_only_ms + xfer_ms + host_work_ms
    # Threads beyond this host's physical cores can't scale the host Adam
    # (the projection models THIS host with a real link, so the local core
    # count is the honest cap even if the knob asks for more).
    threads = min(engine._offload.host_threads, os.cpu_count() or 1)
    if overlap:
        # Overlapped shape: transfers hide behind host Adam (or vice
        # versa), host Adam spreads over the worker pool — device +
        # max(host/threads, transfers), NOT the serial sum. The recorded
        # overlap_fraction is the measured evidence that the pipeline
        # actually hides work.
        proj_ms = device_only_ms + max(host_work_ms / max(1, threads),
                                       xfer_ms)
    else:
        proj_ms = serial_ms
    proj_tps = micro_bs * gas * S / (proj_ms / 1e3)
    return {
        "offload_model": f"gpt2-xl({n_params/1e9:.2f}B)",
        "offload_grad_accum_steps": gas,
        "offload_tokens_per_sec": round(tokens_per_sec, 1),
        "offload_tflops_per_chip": round(tflops, 2),
        "offload_step_wall_ms": round(dt * 1e3, 1),
        "offload_components_ms": {k: round(v, 1) for k, v in comp.items()},
        "offload_components_sum_ms": round(comp_sum_ms, 1),
        "offload_device_only_step_ms": round(device_only_ms, 1),
        "offload_transfer_bytes_each_way": grad_bytes,
        "offload_overlap": {
            "enabled": overlap,
            "host_threads": threads,
            "bucket_mb": bucket_mb,
            "num_buckets": t.get("num_buckets", 1),
            "overlap_fraction": round(t.get("overlap_fraction", 0.0), 4),
            "pipeline_span_ms": round(t.get("pipeline_span_ms", 0.0), 1),
            "pipeline_work_ms": round(t.get("pipeline_work_ms", 0.0), 1),
        },
        "projected_tpu_vm": {
            "assumed_host_link_gb_s": vm_gbs,
            "step_ms": round(proj_ms, 1),
            "tokens_per_sec": round(proj_tps, 1),
            "serial_step_ms": round(serial_ms, 1),
            "formula": "device + max(host/threads, transfers)" if overlap
                       else "device + transfers + host",
        },
    }


def bench_telemetry_overhead(n_steps: int = 40):
    """DS_BENCH_TELEMETRY=1: telemetry enabled-vs-disabled step-time
    overhead (design target < 1%) plus the instrumented device-fence
    counts, on gpt2-tiny. The tiny model makes the denominator a FAST
    step, so the measured fraction is a conservative upper bound for
    real models; equal fence counts are the hard part of the claim (the
    subsystem must add zero per-step host↔device syncs)."""
    import dataclasses
    import tempfile
    from deepspeed_tpu.models import GPT2_CONFIGS, gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh
    import deepspeed_tpu.utils.timer as timer_mod

    cfg = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"],
                              hidden_dropout=0.0, attn_dropout=0.0)
    micro_bs = 4
    n_chips = jax.device_count()
    S = cfg.max_seq_length
    batch = jnp.asarray(np.random.randint(
        0, cfg.vocab_size, size=(micro_bs * n_chips, S + 1), dtype=np.int32))

    def run(enabled: bool):
        tmp = tempfile.mkdtemp(prefix="ds_bench_telemetry_")
        ds = {
            "train_batch_size": micro_bs * n_chips,
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9,
            # report_steps beyond the run: the timed window contains pure
            # hot-path cost, no drain (drains are boundary work by design).
            "telemetry": {"enabled": enabled, "output_path": tmp,
                          "report_steps": 10 ** 9},
        }
        engine = DeepSpeedEngine(model=gpt2_loss_fn(cfg),
                                 model_params=gpt2_init(
                                     jax.random.PRNGKey(0), cfg),
                                 config=ds, mesh=build_mesh())
        for _ in range(4):
            engine.train_batch(batch)
        float(jax.device_get(engine.state.loss_scale))
        sync0 = timer_mod.device_sync_count()
        t0 = time.perf_counter()
        for _ in range(n_steps):
            engine.train_batch(batch)
        float(jax.device_get(engine.state.loss_scale))
        dt_ms = (time.perf_counter() - t0) / n_steps * 1e3
        syncs = timer_mod.device_sync_count() - sync0
        engine.telemetry.close()
        return dt_ms, syncs

    off_ms, off_syncs = run(False)
    on_ms, on_syncs = run(True)
    return {
        "step_ms_disabled": round(off_ms, 4),
        "step_ms_enabled": round(on_ms, 4),
        "overhead_fraction": round((on_ms - off_ms) / max(off_ms, 1e-9), 4),
        "device_syncs_per_run": {"disabled": off_syncs, "enabled": on_syncs},
        "added_device_syncs": on_syncs - off_syncs,
        "n_steps": n_steps,
        "note": "gpt2-tiny denominator — overhead_fraction is a "
                "conservative upper bound for real model sizes, and on "
                "noisy dev hosts it is run-to-run jitter-dominated "
                "(per-step telemetry work is a deque append, ~µs); "
                "added_device_syncs == 0 is the hard claim",
    }


def bench_kernels_ablation(n_steps: int = None):
    """DS_BENCH_KERNELS=1: the ISSUE-8 ablation grid — fused vs unfused
    elementwise kernels x one-pass vs two-pass optimizer update — on the
    bench model (gpt2-large on TPU, gpt2-tiny on the CPU dev box, where
    interpret-mode Pallas timings measure the interpreter, not the
    kernels; the CPU record is a wiring check, the TPU record is the
    ladder evidence; ablate_fused_ln.py carries the analytic projection).

    ``fused_speedup`` (unfused-elementwise two-pass step over fully-fused
    step) is the figure tools/bench_gate.py gates across rounds.
    """
    import dataclasses as _dc
    from deepspeed_tpu.models import gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh

    cfg0, micro_bs = pick_model()
    on_tpu = jax.devices()[0].platform == "tpu"
    if n_steps is None:
        n_steps = 10 if on_tpu else 2
    n_chips = jax.device_count()
    mesh = build_mesh()
    S = cfg0.max_seq_length
    batch = jnp.asarray(np.random.randint(
        0, cfg0.vocab_size, size=(micro_bs * n_chips, S + 1),
        dtype=np.int32))

    def run(fused_ln: bool, one_pass: bool):
        cfg = _dc.replace(cfg0, fused_kernels=fused_ln)
        ds = {
            "train_batch_size": micro_bs * n_chips,
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": 1,
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True,
                     "stochastic_rounding":
                         os.environ.get("DS_BENCH_SR", "1") == "1"},
            "zero_optimization": {"stage": 2},
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-4, "fused": True}},
            "steps_per_print": 10 ** 9,
        }
        engine = DeepSpeedEngine(model=gpt2_loss_fn(cfg),
                                 model_params=gpt2_init(
                                     jax.random.PRNGKey(0), cfg),
                                 config=ds, mesh=mesh)
        if not one_pass:
            # Ablation-only switch: drop back to the historical two-pass
            # sequencing (separate norm read + post-apply select/cast)
            # while keeping the same fused apply kernel. The train step
            # builds lazily, so clearing this BEFORE the first batch is
            # authoritative — assert that invariant so a future eager
            # build turns this into a loud failure, not a silent no-op
            # arm measuring the wrong thing.
            assert engine._train_step_fn is None, \
                "train step already built; two-pass ablation arm invalid"
            engine._fused_step = None
        for _ in range(3):
            engine.train_batch(batch)
        float(jax.device_get(engine.state.loss_scale))
        t0 = time.perf_counter()
        for _ in range(n_steps):
            engine.train_batch(batch)
        float(jax.device_get(engine.state.loss_scale))
        return (time.perf_counter() - t0) / n_steps * 1e3

    grid = {
        "fused_ln+one_pass": run(True, True),
        "fused_ln+two_pass": run(True, False),
        "unfused_ln+one_pass": run(False, True),
        "unfused_ln+two_pass": run(False, False),
    }
    base = grid["unfused_ln+two_pass"]
    best = grid["fused_ln+one_pass"]
    return {
        "model": f"{cfg0.hidden_size}x{cfg0.num_layers}",
        "step_ms": {k: round(v, 2) for k, v in grid.items()},
        "fused_speedup": round(base / max(best, 1e-9), 4),
        "one_pass_only_speedup": round(
            grid["fused_ln+two_pass"] / max(best, 1e-9), 4),
        "elementwise_only_speedup": round(
            grid["unfused_ln+one_pass"] / max(best, 1e-9), 4),
        "measured_on": jax.devices()[0].platform,
        "note": None if on_tpu else (
            "CPU dev box: interpret-mode Pallas — timings measure the "
            "interpreter, not the kernels; see ablate_fused_ln.py for "
            "the analytic projection"),
    }


def offload_extra():
    """Recorded OFFLOAD_BENCH.json if present, else a live run when
    DS_BENCH_OFFLOAD=1, else a skip marker. Never raises."""
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        rec = os.path.join(here, "OFFLOAD_BENCH.json")
        if os.environ.get("DS_BENCH_OFFLOAD") == "1":
            return bench_offload_xl()
        if os.path.exists(rec):
            with open(rec) as f:
                return json.load(f)
        return {"offload_skipped": "no OFFLOAD_BENCH.json; "
                                   "set DS_BENCH_OFFLOAD=1 for a live run"}
    except Exception as e:   # pragma: no cover - bench resilience
        return {"offload_error": f"{type(e).__name__}: {e}"[:200]}


def main():
    from deepspeed_tpu.models import gpt2_init, gpt2_loss_fn
    from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh

    cfg, micro_bs = pick_model()
    n_chips = jax.device_count()
    mesh = build_mesh()  # pure dp over all chips

    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    ds_config = {
        "train_batch_size": micro_bs * n_chips,
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        # DS_BENCH_SR (default on): master-free bf16 with stochastic
        # rounding — drops the fp32 master copy AND the separate
        # cast-param cache, cutting optimizer-step HBM traffic (and
        # freeing the memory the remat=none default needs). Convergence
        # parity vs fp32 masters: tests/test_stochastic_rounding.py.
        "bf16": {"enabled": True,
                 "stochastic_rounding":
                     os.environ.get("DS_BENCH_SR", "1") == "1"},
        "zero_optimization": {"stage": 2},
        # DS_BENCH_FUSED (default on): single-pass Pallas multi-tensor
        # optimizer apply (ops/fused_update.py) — one HBM pass over
        # grad+param+m+v with clip + SR folded in, vs the optax chain's
        # per-leaf fusions. Parity: tests/test_fused_update.py; apply-only
        # delta: ablate_fused_update.py.
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "fused": os.environ.get(
                          "DS_BENCH_FUSED", "1") == "1"}},
        "steps_per_print": 10 ** 9,
    }
    engine = DeepSpeedEngine(model=gpt2_loss_fn(cfg), model_params=params,
                             config=ds_config, mesh=mesh)
    del params   # engine owns fresh buffers; don't pin 3 GB of fp32 masters

    # DS_BENCH_COMM=1: record the audited gradient-sync plan — which
    # lowering the engine actually runs (the hlo_audit probe, not the
    # docstring) and the analytic wire bytes it costs per step. This is
    # the ladder's provenance for the multi-chip scaling claim.
    dp_comm = None
    if os.environ.get("DS_BENCH_COMM") == "1":
        from deepspeed_tpu.parallel import hlo_audit
        wire = hlo_audit.grad_sync_wire_model(engine.state.params,
                                              engine.dp_size)
        mode = engine._grad_sync_mode
        declared = hlo_audit.zero2_grad_sync_lowering(engine.mesh, "data") \
            if engine.dp_size > 1 else "none"
        # Declarative mode on a regressed backend really pays the
        # all-reduce wire — record what the compiled program costs, not
        # what the declaration hoped for.
        reduce_scattered = (mode == "explicit" or
                            (mode == "declarative" and
                             declared == "reduce-scatter"))
        dp_comm = {
            "grad_sync_mode": mode,
            "declared_lowering": declared,
            "grad_wire_bytes_per_step":
                wire["reduce_scatter_wire_bytes"] if reduce_scattered
                else wire["all_reduce_wire_bytes"],
            "wire_model": wire,
        }

    S = cfg.max_seq_length
    # Device-resident batch = what an async input pipeline provides; a numpy
    # arg would be a synchronous H2D transfer inside every dispatch.
    batch = jnp.asarray(np.random.randint(
        0, cfg.vocab_size, size=(micro_bs * n_chips, S + 1), dtype=np.int32))

    # Warmup (compile) + timed steps. Sync via a scalar device_get — on the
    # tunneled axon backend block_until_ready can return early, a host read
    # cannot.
    def sync():
        return float(jax.device_get(engine.state.loss_scale))

    # 4 warmup steps: compile + the throughput-timer's one-time window-start
    # fence (it lands at step 3; timing across it would serialize the
    # pipeline mid-measurement).
    for _ in range(4):
        engine.train_batch(batch)
    sync()
    n_steps = 20 if jax.devices()[0].platform == "tpu" else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        engine.train_batch(batch)   # async dispatch pipelines the steps
    sync()
    dt = (time.perf_counter() - t0) / n_steps

    tokens_per_step = micro_bs * n_chips * S
    tokens_per_sec = tokens_per_step / dt
    flops_per_token = gpt2_flops_per_token(cfg, S)
    tflops_per_chip = tokens_per_sec * flops_per_token / n_chips / 1e12
    frac_peak = tflops_per_chip / chip_peak_tflops()

    # Reference fraction-of-peak: 64 TFLOPs on a 125 TFLOP V100 ≈ 0.512
    # (docs/_posts/2020-05-28-fastest-bert-training.md:15-16).
    ref_frac = 64.0 / 125.0
    record = {
        "metric": f"GPT2({cfg.hidden_size}x{cfg.num_layers}) train TFLOPs/chip",
        "value": round(tflops_per_chip, 2),
        "unit": f"TFLOPs/chip (bf16, {n_chips} chip(s), "
                f"{tokens_per_sec:,.0f} tok/s, {frac_peak:.1%} of peak)",
        "vs_baseline": round(frac_peak / ref_frac, 3),
        # Model-FLOPs utilisation against the shared monitor/peaks.py
        # table (true MFU: analytic model flops/token, remat recompute
        # excluded). tools/bench_gate.py diffs this field across rounds.
        "mfu": round(frac_peak, 4),
        # Ladder provenance: which optimizer apply produced this number.
        "fused_optimizer_apply": ds_config["optimizer"]["params"]["fused"],
    }
    if dp_comm is not None:
        record["dp_comm"] = dp_comm
    # DS_BENCH_KERNELS=1: the fused-elementwise x one/two-pass-optimizer
    # ablation grid (ISSUE 8); `kernels.fused_speedup` is gated by
    # tools/bench_gate.py across rounds. Never fails the bench.
    if os.environ.get("DS_BENCH_KERNELS") == "1":
        try:
            record["kernels"] = bench_kernels_ablation()
        except Exception as e:  # pragma: no cover - bench resilience
            record["kernels"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
    # DS_BENCH_TELEMETRY=1: enabled-vs-disabled telemetry overhead record
    # (<1% target + zero added device fences). Never fails the bench.
    if os.environ.get("DS_BENCH_TELEMETRY") == "1":
        try:
            record["telemetry"] = bench_telemetry_overhead()
        except Exception as e:  # pragma: no cover - bench resilience
            record["telemetry"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
    if jax.devices()[0].platform == "tpu":
        # Free the headline engine's HBM first (a live offload run needs it).
        del engine, batch
        record["extra"] = offload_extra()
    print(json.dumps(record))


if __name__ == "__main__":
    sys.exit(main())
