"""Dev tool: block-sparse attention speedup vs dense-causal flash.

Reproduces the VERDICT metric: BigBird layout, S=32768, D=64, fwd+bwd,
vs the dense causal kernel at the same shapes. Sweeps super-tile factors:
bare ints are k-widening, "QxK" pairs (e.g. 2x4) widen both dims.
Usage: python bench_sparse.py [S] [tiles...]
"""
import math
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu.ops.sparse_flash as sf
from deepspeed_tpu.ops.flash_attention import _flash
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig)

S = int(sys.argv[1]) if len(sys.argv) > 1 else 32768


def _tile(a):
    return tuple(int(x) for x in a.split("x")) if "x" in a else (1, int(a))


TILES = [_tile(a) for a in sys.argv[2:]] or \
    [(1, 1), (1, 4), (2, 2), (2, 4), (4, 2), (2, 8), (4, 4)]
B, NH, D = 1, 4, 64
N = 10

import os
_bb = dict(num_heads=NH,
           block=int(os.environ.get("DS_BENCH_BLOCK", "128")),
           different_layout_per_head=False)
if os.environ.get("DS_BENCH_DENSE_BB") == "1":
    # ~0.105 density at S=32768 (the VERDICT r3 metric point), scaled so
    # density holds across block sizes
    _sc = 128 / _bb["block"]
    _bb.update(num_random_blocks=max(1, int(12 * _sc)),
               num_sliding_window_blocks=max(1, int(9 * _sc)) | 1,
               num_global_blocks=max(1, int(3 * _sc)))
cfg = BigBirdSparsityConfig(**_bb)
layout = np.asarray(cfg.make_layout(S))
density = layout.mean()
key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B * NH, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.fold_in(key, 1), (B * NH, S, D), jnp.bfloat16)
v = jax.random.normal(jax.random.fold_in(key, 2), (B * NH, S, D), jnp.bfloat16)
seed = jnp.zeros((), jnp.int32)
scale = 1.0 / math.sqrt(D)
print(f"S={S} heads={NH} density={density:.3f} "
      f"(ceiling ~{0.5/density:.1f}x vs dense-causal)", flush=True)


def timeit(make_fb):
    @jax.jit
    def many(q):
        def body(c, _):
            return make_fb(c), None
        out, _ = jax.lax.scan(body, q, None, length=N)
        return out
    out = many(q)
    _ = float(jnp.sum(out[0, 0].astype(jnp.float32)))
    t0 = time.perf_counter()
    out = many(q)
    _ = float(jnp.sum(out[0, 0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / N * 1000


def dense_fb(c):
    def f(qq, kk, vv):
        o = _flash(qq, kk, vv, seed, scale, True, 0.0)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(c, k, v)
    return (dq + dk + dv).astype(c.dtype)


def sparse_fb(widen, qwiden):
    def fb(c):
        def f(qq, kk, vv):
            o = sf.sparse_flash_attention(qq, kk, vv, layout, causal=True,
                                          scale=scale, seed=seed,
                                          widen=widen, qwiden=qwiden)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(c, k, v)
        return (dq + dk + dv).astype(c.dtype)
    return fb


t_dense = timeit(dense_fb)
print(f"dense causal : {t_dense:8.1f} ms fwd+bwd", flush=True)
auto = sf.pick_tile(np.asarray(layout), block=S // layout.shape[1])
print(f"pick_tile auto: qw={auto[0]} kw={auto[1]}", flush=True)
for qw, w in TILES:
    lay2 = np.asarray(layout) != 0
    H_, nQ_, nK_ = lay2.shape
    if nK_ % w != 0 or nQ_ % qw != 0 or qw * w > 31:
        print(f"sparse {qw}x{w}: skipped (indivisible or >31 bits)",
              flush=True)
        continue
    nnz_w = sf.supertile_nnz(lay2, qw, w)
    t = timeit(sparse_fb(w, qw))
    print(f"sparse q{qw}xk{w}: {t:8.1f} ms fwd+bwd  ({t_dense/t:4.2f}x vs "
          f"dense; steps/head ~{nnz_w//H_})", flush=True)
