"""Autotune ablation: sweep every Pallas kernel's tile grid (dev tool).

Folds ``ablate_flash.py``'s manual block sweep into the ops/autotune
machinery and extends it to every tiled kernel in the tree: fused
LN/GELU row blocks, flash-attention fwd/bwd (q,k) blocks, and the
grouped-GEMM expert (bm,bn) tiles — the same candidate grids the
resolver searches at first compile on TPU.

Two honest modes (the BENCH_r06 convention):

- **TPU**: runs each kernel at the bench shapes under a FRESH registry,
  letting ``autotune.resolve`` time the grid for real; the recorded
  winners and their ``speedup_vs_heuristic`` come straight out of the
  registry, and the headline ``kernels.tile_speedup`` is their geomean.
  ``--flash-step-sweep`` additionally times the FULL bench train step
  per flash block target (the old ablate_flash.py loop) — block effects
  on the causal skip ratio only show at step level.
- **CPU dev box**: interpret-mode Pallas times the interpreter, not the
  kernel, so nothing is timed. The record lists each kernel's candidate
  grid and heuristic choice (the structural content: what a TPU session
  will search) and claims ``tile_speedup`` = 1.0 — the autotuner can
  only match-or-beat the heuristic it falls back to, so parity is the
  only honest CPU projection. Labeled ``projected`` throughout.

``--record`` writes BENCH_r07.json (driver round shape), carrying
forward BENCH_r06's measured/projected step headline so
``tools/bench_gate.py`` keeps comparing mfu and ``fused_speedup``
across rounds; the new ``kernels.tile_speedup`` field is gated by
``--tile-drop`` (pre-autotune rounds skip, never fail).

Usage: python ablate_autotune.py [--record] [--flash-step-sweep]
"""
import json
import math
import os
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops import autotune
from deepspeed_tpu.ops import fused_elementwise as fe
from deepspeed_tpu.ops import flash_attention as fa
from deepspeed_tpu.ops import grouped_gemm as gg

REPO = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(REPO, "BENCH_r07.json")
PREV = os.path.join(REPO, "BENCH_r06.json")
RECORD = "--record" in sys.argv
FLASH_STEP_SWEEP = "--flash-step-sweep" in sys.argv

# Bench shapes: the gpt2-large DS_BENCH configuration (bench.py) and the
# moe ablation's dispatched expert shapes (ablate_moe.py).
MBS, S, HEADS, D = 4, 1024, 20, 64
H, F = 1280, 5120
E, CAP, MH, MF = 8, 50, 128, 512


def _geom_heuristic(Hdim: int, n_bufs: int) -> int:
    """The static budget loop _geom falls back to (DS_AUTOTUNE=0)."""
    Hpad = -(-Hdim // fe._LANE) * fe._LANE
    rb = 128
    while rb > 16 and rb * Hpad * 4 * n_bufs > fe._VMEM_BUDGET:
        rb //= 2
    return rb


def sweep_entries():
    """(kernel, shape, dtype, heuristic, candidates, runner) per tile
    decision at the bench shapes. ``runner(tile)`` executes the real
    driver with the tile PINNED (the drivers' own recursion-guard
    params) — on TPU ``autotune.measure_from_runner`` times it."""
    rows = MBS * S
    out = []

    def ln_runner(kernel, n_bufs, dtype):
        x = jnp.zeros((rows, H), dtype)
        v = jnp.zeros((H,), jnp.float32)
        if kernel == "fused_ln_fwd":
            return lambda rb: fe._ln_forward(x, None, v, v, 1e-5, _rb=rb)
        return lambda rb: fe._ln_backward(x, v, x, None, 1e-5, _rb=rb)

    def gelu_runner(kernel, dtype):
        y = jnp.zeros((rows, F), dtype)
        b = jnp.zeros((F,), jnp.float32)
        if kernel == "fused_gelu_fwd":
            return lambda rb: fe._gelu_apply(y, b, False, _rb=rb)
        return lambda rb: fe._fbg_bwd_impl(y, b, y, False, _rb=rb)

    for dtype in (jnp.bfloat16,):
        dname = str(jnp.dtype(dtype))
        for kernel, n_bufs, Hdim in [("fused_ln_fwd", 5, H),
                                     ("fused_ln_bwd", 6, H)]:
            Hpad = -(-Hdim // fe._LANE) * fe._LANE
            cands = autotune.pow2_candidates(
                16, 256,
                lambda c: c * Hpad * 4 * n_bufs <= fe._VMEM_BUDGET)
            out.append((kernel, (rows, Hdim, n_bufs), dname,
                        _geom_heuristic(Hdim, n_bufs), cands,
                        ln_runner(kernel, n_bufs, dtype)))
        for kernel, n_bufs in [("fused_gelu_fwd", 4),
                               ("fused_gelu_bwd", 5)]:
            Fpad = -(-F // fe._LANE) * fe._LANE
            cands = autotune.pow2_candidates(
                16, 256,
                lambda c: c * Fpad * 4 * n_bufs <= fe._VMEM_BUDGET)
            out.append((kernel, (rows, F, n_bufs), dname,
                        _geom_heuristic(F, n_bufs), cands,
                        gelu_runner(kernel, dtype)))

    # Flash fwd/bwd: (bq, bk) over the causal bench sequence. The old
    # ablate_flash.py swept _BLOCK_TARGET at step level; this is the
    # same grid per kernel call, resolver-shaped.
    BH = MBS * HEADS
    q = jnp.zeros((BH, S, D), jnp.bfloat16)
    cands2 = [(bq, bk) for bq in fa._block_candidates(S)
              for bk in fa._block_candidates(S)]
    heur_f = (fa._pick_block(S), fa._pick_block(S))
    heur_b = (fa._pick_block(S, fa._BLOCK_TARGET_BWD),
              fa._pick_block(S, fa._BLOCK_TARGET_BWD))
    out.append(("flash_fwd", (BH, S, S, D, 1), "bfloat16", heur_f,
                cands2,
                lambda t: fa._flash_fwd(q, q, q, None, True, 1.0,
                                        _blocks=t)))

    def flash_bwd_runner(t):
        o = jnp.zeros((BH, S, D), jnp.bfloat16)
        lse = jnp.zeros((BH, 1, S), jnp.float32)
        return fa._flash_bwd(q, q, q, None, o, lse, o, True, 1.0,
                             _blocks=t)

    out.append(("flash_bwd", (BH, S, S, D, 1), "bfloat16", heur_b,
                cands2, flash_bwd_runner))

    # Grouped-GEMM expert tiles at the dispatched moe shapes (both
    # stages of the FFN: [E,C,H]x[E,H,F] and [E,C,F]x[E,F,H]).
    for (M, K_, N) in [(CAP, MH, MF), (CAP, MF, MH)]:
        a = jnp.zeros((E, M, K_), jnp.float32)
        b = jnp.zeros((E, K_, N), jnp.float32)
        out.append(("grouped_gemm", (E, M, K_, N), "float32",
                    gg._tile_heuristic(M, K_, N, 4),
                    list(gg._tile_candidates(M, K_, N)),
                    lambda t, a=a, b=b: gg._grouped_matmul(a, b,
                                                           _tile=t)))
    return out


def flash_step_sweep(blocks=(1024, 512, 256)):
    """The old ablate_flash.py loop: full bench train step per flash
    block target (TPU only — step walls on CPU time the interpreter)."""
    import dataclasses
    import functools
    import time

    import optax

    from deepspeed_tpu.models import GPT2_CONFIGS
    from deepspeed_tpu.models.gpt2 import (gpt2_flops_per_token,
                                           gpt2_init, gpt2_loss_fn)

    cfg = dataclasses.replace(GPT2_CONFIGS["gpt2-large"],
                              max_seq_length=S, remat_policy="dots",
                              hidden_dropout=0.0, attn_dropout=0.0,
                              scan_layers=False)
    loss_fn = gpt2_loss_fn(cfg)
    tx = optax.adamw(1e-4)

    def cast(p):
        return jax.tree_util.tree_map(
            lambda a: a.astype(cfg.dtype)
            if a.dtype == jnp.float32 else a, p)

    results = {}
    for block in blocks:
        fa._BLOCK_TARGET = block
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        opt_state = tx.init(params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, batch, rng):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(cast(p), batch, rng))(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        batch = jnp.asarray(np.random.randint(
            0, cfg.vocab_size, size=(MBS, S + 1), dtype=np.int32))
        rng = jax.random.PRNGKey(1)
        params, opt_state, loss = step(params, opt_state, batch, rng)
        _ = float(loss)
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, batch, rng)
        _ = float(loss)
        dt = (time.perf_counter() - t0) / n
        tf = MBS * S / dt * gpt2_flops_per_token(cfg, S) / 1e12
        results[block] = {"ms_per_step": round(dt * 1000, 2),
                          "tflops_per_chip": round(tf, 1)}
        print(f"flash block={block:5d}: {dt*1000:7.1f} ms/step "
              f"{tf:6.1f} TFLOPs", flush=True)
        del params, opt_state
    return results


def main():
    on_tpu = jax.default_backend() == "tpu"
    entries = sweep_entries()
    table = []
    speedups = []
    if on_tpu:
        # Fresh registry: this run's searches, nothing stale.
        reg = tempfile.mktemp(prefix="autotune_ablate_", suffix=".json")
        os.environ["DS_AUTOTUNE_REGISTRY"] = reg
        os.environ.pop("DS_AUTOTUNE", None)
        autotune.reset()
        for kernel, shape, dname, heur, cands, runner in entries:
            win = autotune.resolve(kernel, shape, dname, heur, cands,
                                   autotune.measure_from_runner(runner))
            ent = autotune._load(reg).get(
                autotune._key(kernel, shape, dname), {})
            sp = ent.get("speedup_vs_heuristic") or 1.0
            speedups.append(sp)
            table.append({"kernel": kernel, "shape": list(shape),
                          "dtype": dname, "heuristic":
                          autotune._encode(heur),
                          "winner": autotune._encode(win),
                          "speedup_vs_heuristic": sp,
                          "candidates": len(cands)})
            print(f"{kernel:>16} {shape}: heuristic="
                  f"{heur} winner={win} ({sp:.4f}x)", flush=True)
    else:
        for kernel, shape, dname, heur, cands, _ in entries:
            table.append({"kernel": kernel, "shape": list(shape),
                          "dtype": dname,
                          "heuristic": autotune._encode(heur),
                          "winner": autotune._encode(heur),
                          "speedup_vs_heuristic": 1.0,
                          "candidates": len(cands)})
            print(f"{kernel:>16} {shape}: heuristic={heur} "
                  f"({len(cands)} candidates, search deferred to TPU)",
                  flush=True)
    tile_speedup = round(
        math.exp(sum(math.log(max(s, 1e-9)) for s in speedups)
                 / len(speedups)), 4) if speedups else 1.0

    step_sweep = None
    if FLASH_STEP_SWEEP and on_tpu:
        step_sweep = flash_step_sweep()
    elif FLASH_STEP_SWEEP:
        print("--flash-step-sweep skipped: step walls on CPU time the "
              "interpreter, not the kernel")

    # Carry BENCH_r06's step headline forward so the mfu/fused_speedup
    # gates keep comparing; a TPU session overwrites it measured.
    parsed = {}
    try:
        with open(PREV) as f:
            prev = json.load(f).get("parsed", {})
        parsed.update(prev)
    except (OSError, json.JSONDecodeError):
        prev = {}
    kernels = dict(parsed.get("kernels") or {})
    kernels["tile_speedup"] = tile_speedup
    kernels["autotune"] = {
        "projected": not on_tpu,
        "chip": autotune.chip_kind(),
        "sweep": table,
        "note": ("measured by ops/autotune.resolve under a fresh "
                 "registry" if on_tpu else
                 "PROJECTED on the CPU dev box: candidate grids and "
                 "heuristic choices are the structural record; 1.0 is "
                 "the only honest CPU claim (the autotuner falls back "
                 "to exactly these heuristics, and can only match-or-"
                 "beat them when a TPU session searches). Re-record on "
                 "TPU: python ablate_autotune.py --record"),
    }
    if step_sweep:
        kernels["autotune"]["flash_step_sweep"] = step_sweep
    parsed["kernels"] = kernels
    record = {
        "n": 7,
        "cmd": "python ablate_autotune.py --record",
        "rc": 0,
        "tail": json.dumps({"kernel_sweeps": len(table),
                            "tile_speedup": tile_speedup,
                            "projected": not on_tpu}),
        "parsed": parsed,
    }
    print(json.dumps({"tile_speedup": tile_speedup,
                      "sweeps": len(table),
                      "projected": not on_tpu}, indent=1))
    if RECORD:
        with open(OUT, "w") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
