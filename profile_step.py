"""Dev tool: component-level timing of one GPT-2 train step on the real chip.

Times fwd-only, fwd+bwd, and full step for a config, with dummy-loss and
dense-attention toggles, to locate where the step time goes.
Usage: python profile_step.py [model] [mbs] [remat]
"""
import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import (gpt2_apply, gpt2_init,
                                       gpt2_flops_per_token)

MODEL = sys.argv[1] if len(sys.argv) > 1 else "gpt2-medium"
MBS = int(sys.argv[2]) if len(sys.argv) > 2 else 4
REMAT = sys.argv[3] if len(sys.argv) > 3 else "dots"

cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024,
                          remat_policy=REMAT, hidden_dropout=0.0,
                          attn_dropout=0.0)
S = cfg.max_seq_length


def timeit(fn, *args, n=10):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1000


def ce_full(logits, targets):
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.mean(-jnp.take_along_axis(logp, targets[..., None], axis=-1))


def main():
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    batch = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                          size=(MBS, S + 1), dtype=np.int32))
    rng = jax.random.PRNGKey(1)
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)

    def loss(p, dummy=False):
        tokens, targets = batch[:, :-1], batch[:, 1:]
        if dummy:
            from deepspeed_tpu.models.transformer import apply_blocks, layer_norm
            x = p["wte"].astype(cfg.dtype)[tokens] + \
                p["wpe"].astype(cfg.dtype)[None, :S]
            x = apply_blocks(p["blocks"], x, cfg, rng=rng, deterministic=False)
            x = layer_norm(x, p["ln_f_scale"], p["ln_f_bias"], cfg.layer_norm_eps)
            return jnp.mean(x.astype(jnp.float32) ** 2)
        logits = gpt2_apply(p, tokens, cfg, rng=rng, deterministic=False)
        return ce_full(logits, targets)

    def cast(p):
        return jax.tree_util.tree_map(
            lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a, p)

    fwd = jax.jit(lambda p: loss(cast(p)))
    fwd_dummy = jax.jit(lambda p: loss(cast(p), dummy=True))
    grad = jax.jit(lambda p: jax.value_and_grad(lambda q: loss(cast(q)))(p))
    grad_dummy = jax.jit(
        lambda p: jax.value_and_grad(lambda q: loss(cast(q), dummy=True))(p))

    @jax.jit
    def opt_only(p, o, g):
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o

    tok = MBS * S
    fl = tok * gpt2_flops_per_token(cfg, S) / 1e12

    t_fwd = timeit(fwd, params)
    t_fwdd = timeit(fwd_dummy, params)
    _, g = grad(params)
    t_grad = timeit(grad, params)
    t_gradd = timeit(grad_dummy, params)
    t_opt = timeit(opt_only, params, opt_state, g)

    print(f"{MODEL} mbs={MBS} remat={REMAT}  (total train flops {fl:.1f} TF)")
    print(f"  fwd(CE)     : {t_fwd:7.1f} ms   fwd(dummy): {t_fwdd:7.1f} ms  "
          f"-> CE head fwd {t_fwd - t_fwdd:5.1f} ms")
    print(f"  fwd+bwd(CE) : {t_grad:7.1f} ms   f+b(dummy): {t_gradd:7.1f} ms  "
          f"-> CE head f+b {t_grad - t_gradd:5.1f} ms")
    print(f"  adamw step  : {t_opt:7.1f} ms")
    print(f"  full ~= {t_grad + t_opt:.1f} ms -> "
          f"{fl / (t_grad + t_opt) * 1000:.1f} TFLOPs")


if __name__ == "__main__":
    main()
