#!/usr/bin/env python
"""Summarize a telemetry JSONL run into TELEMETRY.json.

Usage:
    python tools/telemetry_report.py runs/MyJob.jsonl [-o TELEMETRY.json]

Reads the line records the monitor/ subsystem emits (kind: meta | step |
report | event) and produces one machine-diffable summary so benches and
CI can compare runs:

- step time p50/p95/mean (ms) — per-step host wall. On the jitted paths
  this is DISPATCH wall (steps pipeline asynchronously); the fenced
  ground truth is ``throughput.samples_per_sec`` from the report
  record's synchronized window average.
- throughput (samples/sec, window-averaged) and total samples.
- recompile count + the offending functions/signature deltas.
- peak device memory vs the analytic ZeRO model-state footprint (and any
  watermark events). ``memory.available: false`` when the backend
  reports no ``memory_stats()`` (e.g. CPU).
- wire bytes/step from the grad-sync wire model, with a consistency
  check between the meta record and the per-step records.
- overflow/skipped-step counts and dropped-record accounting (a ring
  overflow between drains is reported, never silent).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (no numpy dep so
    the tool runs anywhere)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def summarize(jsonl_path: str) -> Dict[str, Any]:
    """Summary of the LATEST run in the stream: the sink appends (so a
    resumed/re-launched job with the same job_name extends one file), and
    every run opens with a ``meta`` record — seeing one resets the
    accumulators so earlier runs' steps can't contaminate this run's
    percentiles, recompile counts, or consistency checks."""
    meta: Dict[str, Any] = {}
    steps: List[Dict[str, Any]] = []
    reports: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = rec.get("kind")
            if kind == "meta":
                meta, steps, reports, events = dict(rec), [], [], []
            elif kind == "step":
                steps.append(rec)
            elif kind == "report":
                reports.append(rec)
            elif kind == "event":
                events.append(rec)

    walls = sorted(float(r["wall_ms"]) for r in steps if "wall_ms" in r)
    recompiles = [e for e in events if e.get("event") == "recompile"]
    watermarks = [e for e in events if e.get("event") == "memory_watermark"]

    # Throughput: the last report with a closed (valid) window wins.
    samples_per_sec: Optional[float] = None
    for rep in reversed(reports):
        if rep.get("samples_per_sec_valid"):
            samples_per_sec = float(rep["samples_per_sec"])
            break

    # Wire bytes: meta is authoritative; per-step records must agree.
    wire_meta = meta.get("wire_bytes_per_step")
    step_wires = {int(r["wire_bytes"]) for r in steps if "wire_bytes" in r}
    wire_consistent = (wire_meta is None and not step_wires) or \
        (wire_meta is not None and
         (not step_wires or step_wires == {int(wire_meta)}))

    # Memory: peak across every drain sample vs the analytic footprint.
    peaks = [int(rep["memory"]["peak_bytes_in_use_max"]) for rep in reports
             if isinstance(rep.get("memory"), dict)
             and "peak_bytes_in_use_max" in rep["memory"]]
    analytic = meta.get("analytic_state_bytes")
    memory: Dict[str, Any] = {"available": bool(peaks)}
    if analytic is not None:
        memory["analytic_state_bytes"] = int(analytic)
    if peaks:
        memory["peak_bytes_in_use_max"] = max(peaks)
        if analytic:
            memory["peak_vs_analytic_ratio"] = round(
                max(peaks) / max(1, int(analytic)), 4)
    memory["watermark_events"] = len(watermarks)

    overflows = sum(1 for r in steps if r.get("overflow"))
    skipped = None
    for rep in reversed(reports):
        if "skipped_steps" in rep:
            skipped = int(rep["skipped_steps"])
            break

    offload_steps = [r["offload"] for r in steps
                     if isinstance(r.get("offload"), dict)]
    offload: Optional[Dict[str, Any]] = None
    if offload_steps:
        fracs = [float(o.get("overlap_fraction", 0.0))
                 for o in offload_steps]
        offload = {
            "steps": len(offload_steps),
            "overlap_fraction_mean": round(sum(fracs) / len(fracs), 4),
            "num_buckets": offload_steps[-1].get("num_buckets"),
            "overlapped": offload_steps[-1].get("overlapped"),
        }

    return {
        "source": os.path.basename(jsonl_path),
        "meta": {k: v for k, v in meta.items() if k not in ("kind", "ts")},
        "steps_recorded": len(steps),
        "dropped_records": sum(int(rep.get("dropped_records", 0))
                               for rep in reports),
        "step_time_ms": {
            "p50": round(_percentile(walls, 50), 3),
            "p95": round(_percentile(walls, 95), 3),
            "mean": round(sum(walls) / len(walls), 3) if walls else 0.0,
            "n": len(walls),
            "note": "host wall per train_batch: dispatch wall on jitted "
                    "paths, true wall on the host-synchronous offload path",
        },
        "throughput": {
            "samples_per_sec": samples_per_sec,
            "window_valid": samples_per_sec is not None,
        },
        "recompiles": {
            "count": len(recompiles),
            "events": [{"fn": e.get("fn"),
                        "step": e.get("step"),
                        "signature_delta": e.get("signature_delta")}
                       for e in recompiles],
        },
        "memory": memory,
        "wire_bytes_per_step": wire_meta,
        "wire_bytes_consistent": wire_consistent,
        "overflow_steps": overflows,
        "skipped_steps": skipped,
        "offload": offload,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="telemetry JSONL stream to summarize")
    ap.add_argument("-o", "--output", default="TELEMETRY.json",
                    help="summary output path (default TELEMETRY.json)")
    args = ap.parse_args(argv)
    summary = summarize(args.jsonl)
    with open(args.output, "w") as f:
        json.dump(summary, f, indent=2)
    st = summary["step_time_ms"]
    print(f"{args.output}: {summary['steps_recorded']} steps, "
          f"p50={st['p50']}ms p95={st['p95']}ms, "
          f"recompiles={summary['recompiles']['count']}, "
          f"watermarks={summary['memory']['watermark_events']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
