#!/usr/bin/env python
"""Summarize a telemetry JSONL run into TELEMETRY.json.

Usage:
    python tools/telemetry_report.py runs/MyJob.jsonl [-o TELEMETRY.json]

Reads the line records the monitor/ subsystem emits (kind: meta | step |
report | event | cost_model) and produces one machine-diffable summary
so benches and CI can compare runs:

- step time p50/p95/mean (ms) — per-step host wall. On the jitted paths
  this is DISPATCH wall (steps pipeline asynchronously); the fenced
  ground truth is ``throughput.samples_per_sec`` from the report
  record's synchronized window average.
- throughput (samples/sec, window-averaged) and total samples.
- recompile count + the offending functions/signature deltas.
- peak device memory vs the analytic ZeRO model-state footprint (and any
  watermark events). ``memory.available: false`` when the backend
  reports no ``memory_stats()`` (e.g. CPU).
- wire bytes/step from the grad-sync wire model, with a consistency
  check between the meta record and the per-step records.
- overflow/skipped-step counts and dropped-record accounting (a ring
  overflow between drains is reported, never silent).
- ``mfu``: per-step MFU stats (dispatch-wall based) plus the fenced
  ``window_mfu`` from the last closed throughput window.
- ``roofline``: the cost_model record's per-path verdicts
  (compute/HBM/interconnect-bound), the fused per-step analytic floor,
  and measured-p50 vs floor (how far the run sits from the ceiling).
- ``goodput``: bucket totals aggregated across every settled window,
  the goodput fraction, and the sum-to-wall consistency verdict.
- ``serving``: present when the stream came from the inference tier
  (meta ``mode: "serving"`` or serving-shaped records): batch occupancy
  over decode iterations, TTFT/TPOT p50/p95 from ``request_complete``
  events, tokens/s and decode-step percentiles from the last report's
  aggregator snapshot.
- ``serving_slo``: request-scoped observability for serving streams —
  per-replica serving goodput ledger (prefill / decode_useful /
  spec_wasted / admission_blocked / idle buckets summing to the serve
  wall, with a double-attribution ``consistent`` verdict), SLO
  attainment + burn-rate verdicts per replica (``slo: null`` with a
  reason when no request completed or no target is configured — never
  a crash), and the slowest-TTFT request exemplars with their full
  span timelines from the ``request_trace`` events, audited for
  contiguity (spans must tile [0, total_ms] with no gaps/overlaps).
- ``moe``: present when the run carried MoE metrics (the engine's
  ``moe`` config block): drop-fraction p50/p95/last, expert-load
  imbalance (max/mean routed counts — 1.0 is balanced), last aux loss,
  and the analytic all-to-all wire bytes/step from the meta record.
  ``tools/bench_gate.py`` gates drop-fraction rises across rounds.
- ``health``: anomaly counts (non-finite provenance events, EWMA
  spikes), watchdog fires, flight-recorder presence (FLIGHT.json next
  to the stream, with its recorded reason), the ``truncated`` verdict,
  and multi-host aggregation over per-host shards
  (``<job>.rankK.jsonl``): per-host step-wall p50 with straggler skew,
  step-count desync, and loss-hash desync (SPMD processes must see the
  same loss — a differing hash means the pod diverged).
- ``truncated`` (top level): a marker-capable stream (meta
  ``emits_final``) whose latest segment lacks the terminal ``final``
  record ended in a crash/kill — its window stats describe a PARTIAL
  run and are labeled so instead of being reported as a complete one.
  Pre-marker streams get ``null`` (unknown), never a false verdict.

``tools/bench_gate.py`` diffs the mfu/goodput sections across bench
rounds — and the serving section across serving rounds — and fails CI
on regression; a ``health`` section with non-finite anomalies, watchdog
fires, or a truncated stream fails the round outright.
"""
from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (no numpy dep so
    the tool runs anywhere)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def _parse_segment(jsonl_path: str) -> Tuple[Dict[str, Any],
                                             List[Dict[str, Any]],
                                             List[Dict[str, Any]],
                                             List[Dict[str, Any]],
                                             Dict[str, Any], bool]:
    """(meta, steps, reports, events, cost_model, saw_final) of the
    LATEST segment in an append-mode stream (a meta record resets)."""
    meta: Dict[str, Any] = {}
    steps: List[Dict[str, Any]] = []
    reports: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    cost_model: Dict[str, Any] = {}
    saw_final = False
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = rec.get("kind")
            if kind == "meta":
                meta, steps, reports, events = dict(rec), [], [], []
                cost_model = {}
                saw_final = False
            elif kind == "step":
                steps.append(rec)
            elif kind == "report":
                reports.append(rec)
            elif kind == "event":
                events.append(rec)
            elif kind == "cost_model":
                cost_model = dict(rec)
            elif kind == "final":
                saw_final = True
    return meta, steps, reports, events, cost_model, saw_final


def _loss_hash(steps: List[Dict[str, Any]]) -> Optional[str]:
    """Order-sensitive digest of the (rounded) loss series — SPMD
    processes compute the same global loss, so differing hashes across
    host shards mean the pod DIVERGED (desync), the check no per-host
    eyeball could do."""
    losses = [round(float(r["loss"]), 5) for r in steps
              if isinstance(r.get("loss"), (int, float))
              and not isinstance(r.get("loss"), bool)]
    if not losses:
        return None
    return hashlib.md5(json.dumps(losses).encode()).hexdigest()[:12]


def _host_entry(rank: int, steps: List[Dict[str, Any]],
                saw_final: bool) -> Dict[str, Any]:
    walls = sorted(float(r["wall_ms"]) for r in steps if "wall_ms" in r)
    return {"rank": rank, "steps": len(steps),
            "last_step": steps[-1].get("step") if steps else None,
            "wall_p50_ms": round(_percentile(walls, 50), 3),
            "loss_hash": _loss_hash(steps),
            "final": bool(saw_final)}


def aggregate_hosts(jsonl_path: str, meta: Dict[str, Any],
                    steps: List[Dict[str, Any]],
                    saw_final: bool) -> Dict[str, Any]:
    """Cross-host view from the per-host shards next to the primary
    stream: straggler skew (per-host step-wall p50 spread), step-count
    desync, and loss-hash desync."""
    root, ext = os.path.splitext(jsonl_path)
    shard_paths = sorted(glob.glob(f"{root}.rank*{ext}"))
    entries = [_host_entry(int(meta.get("process_index", 0) or 0),
                           steps, saw_final)]
    # Stale-shard guard: the sink appends, so a relaunch with a smaller
    # world (or per_host_shards off) leaves orphaned rank files whose
    # LAST segment belongs to the previous run — comparing them against
    # the new primary would fabricate desync/straggler verdicts. A shard
    # is stale when its rank falls outside the primary's process_count,
    # or its segment-start ts is far (>15 min) from the primary's —
    # SPMD processes of one run start near-simultaneously.
    primary_ts = float(meta.get("ts") or 0.0)
    pcount = int(meta.get("process_count") or 0)
    stale: List[Dict[str, Any]] = []
    for p in shard_paths:
        m = re.search(r"\.rank(\d+)" + re.escape(ext) + "$", p)
        meta_s, steps_s, _, _, _, fin_s = _parse_segment(p)
        rank = int(meta_s.get("process_index",
                              m.group(1) if m else -1) or 0)
        ts_s = float(meta_s.get("ts") or 0.0)
        reason = None
        if pcount and rank >= pcount:
            reason = f"rank {rank} outside process_count {pcount}"
        elif primary_ts and ts_s and abs(ts_s - primary_ts) > 900.0:
            reason = "segment start >15min from the primary's"
        if reason is not None:
            stale.append({"rank": rank, "path": os.path.basename(p),
                          "reason": reason})
            continue
        entries.append(_host_entry(rank, steps_s, fin_s))
    out: Dict[str, Any] = {"available": len(entries) > 1,
                           "n_hosts": len(entries)}
    if stale:
        out["stale_shards"] = stale
    if len(entries) < 2:
        return out
    entries.sort(key=lambda e: e["rank"])
    p50s = [e["wall_p50_ms"] for e in entries if e["wall_p50_ms"] > 0]
    skew = None
    slowest = None
    if p50s and min(p50s) > 0:
        skew = round((max(p50s) - min(p50s)) / min(p50s), 4)
        slowest = max((e for e in entries if e["wall_p50_ms"] > 0),
                      key=lambda e: e["wall_p50_ms"])["rank"]
    lasts = {e["last_step"] for e in entries if e["last_step"] is not None}
    hashes = {e["loss_hash"] for e in entries if e["loss_hash"]}
    out.update({
        "per_host": entries,
        "straggler_skew_rel": skew,
        "slowest_rank": slowest,
        "step_count_desync": len(lasts) > 1,
        "loss_desync": len(hashes) > 1,
    })
    return out


def summarize(jsonl_path: str) -> Dict[str, Any]:
    """Summary of the LATEST run in the stream: the sink appends (so a
    resumed/re-launched job with the same job_name extends one file), and
    every run opens with a ``meta`` record — seeing one resets the
    accumulators so earlier runs' steps can't contaminate this run's
    percentiles, recompile counts, or consistency checks."""
    meta, steps, reports, events, cost_model, saw_final = \
        _parse_segment(jsonl_path)

    walls = sorted(float(r["wall_ms"]) for r in steps if "wall_ms" in r)
    recompiles = [e for e in events if e.get("event") == "recompile"]
    watermarks = [e for e in events if e.get("event") == "memory_watermark"]

    # Throughput: the last report with a closed (valid) window wins.
    samples_per_sec: Optional[float] = None
    for rep in reversed(reports):
        if rep.get("samples_per_sec_valid"):
            samples_per_sec = float(rep["samples_per_sec"])
            break

    # Wire bytes: meta is authoritative; per-step records must agree.
    wire_meta = meta.get("wire_bytes_per_step")
    step_wires = {int(r["wire_bytes"]) for r in steps if "wire_bytes" in r}
    wire_consistent = (wire_meta is None and not step_wires) or \
        (wire_meta is not None and
         (not step_wires or step_wires == {int(wire_meta)}))

    # Memory: peak across every drain sample vs the analytic footprint.
    peaks = [int(rep["memory"]["peak_bytes_in_use_max"]) for rep in reports
             if isinstance(rep.get("memory"), dict)
             and "peak_bytes_in_use_max" in rep["memory"]]
    analytic = meta.get("analytic_state_bytes")
    memory: Dict[str, Any] = {"available": bool(peaks)}
    if analytic is not None:
        memory["analytic_state_bytes"] = int(analytic)
    if peaks:
        memory["peak_bytes_in_use_max"] = max(peaks)
        if analytic:
            memory["peak_vs_analytic_ratio"] = round(
                max(peaks) / max(1, int(analytic)), 4)
    memory["watermark_events"] = len(watermarks)

    overflows = sum(1 for r in steps if r.get("overflow"))
    skipped = None
    for rep in reversed(reports):
        if "skipped_steps" in rep:
            skipped = int(rep["skipped_steps"])
            break

    # MoE section: per-step expert load-balance stats from the moe_*
    # metrics the engine rides on the drain (meta `moe` block = the
    # config truth). Imbalance = max/mean of the per-expert routed
    # token counts — 1.0 is perfectly balanced; bench_gate diffs the
    # drop-fraction percentiles across rounds.
    moe: Dict[str, Any] = {"available": False}
    moe_steps = [r for r in steps if "moe_drop_fraction" in r]
    if moe_steps:
        drops = sorted(float(r["moe_drop_fraction"]) for r in moe_steps)
        aux = [float(r["moe_aux_loss"]) for r in moe_steps
               if "moe_aux_loss" in r]
        imbalance = []
        for r in moe_steps:
            counts = r.get("moe_expert_tokens")
            if isinstance(counts, list) and counts:
                mean = sum(counts) / len(counts)
                if mean > 0:
                    imbalance.append(max(counts) / mean)
        moe = {
            "available": True,
            "config": meta.get("moe") or {},
            "ep": meta.get("ep"),
            "steps": len(moe_steps),
            "drop_fraction": {
                "p50": round(_percentile(drops, 50), 5),
                "p95": round(_percentile(drops, 95), 5),
                "last": round(drops and float(
                    moe_steps[-1]["moe_drop_fraction"]) or 0.0, 5),
            },
            "aux_loss_last": round(aux[-1], 5) if aux else None,
            "expert_imbalance": {
                "p50": round(_percentile(sorted(imbalance), 50), 4),
                "max": round(max(imbalance), 4) if imbalance else None,
            } if imbalance else {"p50": None, "max": None},
            "alltoall_wire_bytes_per_step":
                meta.get("moe_alltoall_wire_bytes_per_step"),
        }

    # MFU: per-step figures are dispatch-wall based (honest but loose on
    # jitted paths); window_mfu comes from the fenced throughput window.
    step_mfus = [float(r["mfu"]) for r in steps if "mfu" in r]
    window_mfu: Optional[float] = None
    for rep in reversed(reports):
        if "window_mfu" in rep:
            window_mfu = float(rep["window_mfu"])
            break
    mfu: Dict[str, Any] = {"available": bool(step_mfus)}
    if step_mfus:
        s = sorted(step_mfus)
        mfu.update({
            "per_step_mean": float(f"{sum(s) / len(s):.4g}"),
            "per_step_p50": float(f"{_percentile(s, 50):.4g}"),
            "n": len(s),
        })
    if window_mfu is not None:
        mfu["window_mfu"] = window_mfu
    chip = cost_model.get("chip") or {}
    if chip:
        mfu["peak_bf16_tflops"] = chip.get("bf16_tflops")
        mfu["peak_assumed"] = bool(chip.get("assumed"))

    # Roofline: the cost_model record, slimmed to the decision fields,
    # plus measured-vs-floor (dispatch p50 over the analytic floor — how
    # far the run sits from the perfect-overlap ceiling; <1 would mean
    # the model is wrong or the wall clock lies).
    roofline: Dict[str, Any] = {"available": bool(cost_model)}
    if cost_model:
        cm_step = cost_model.get("step") or {}
        paths = {}
        for name, p in (cost_model.get("paths") or {}).items():
            if not isinstance(p, dict):
                continue
            paths[name] = {k: p.get(k) for k in
                           ("bound", "floor_ms", "t_compute_ms", "t_hbm_ms",
                            "t_comm_ms", "t_dcn_ms", "scan_scale",
                            "available")
                           if k in p}
        roofline.update({
            "chip": chip,
            "n_devices": cost_model.get("n_devices"),
            "paths": paths,
            "step_bound": cm_step.get("bound"),
            "step_floor_ms": cm_step.get("floor_ms"),
            "flops_per_step": cm_step.get("flops_per_step"),
            "missing_paths": cm_step.get("missing_paths"),
        })
        # Two-tier interconnect verdict (multislice runs): the wire
        # bytes each tier moves per step (telemetry meta) and which
        # tier binds comm — a step can be DCN-bound while ICI idles,
        # and the fused t_comm figure alone would hide it.
        if int(meta.get("slices") or 1) > 1:
            t_ici = sum((p.get("t_comm_ms") or 0.0) for p in paths.values())
            t_dcn = sum((p.get("t_dcn_ms") or 0.0) for p in paths.values())
            roofline["comm_tiers"] = {
                "slices": int(meta["slices"]),
                "wire_bytes_ici": meta.get("wire_bytes_ici"),
                "wire_bytes_dcn": meta.get("wire_bytes_dcn"),
                "dcn_compression": bool(meta.get("dcn_compression")),
                "t_ici_ms": round(t_ici, 6),
                "t_dcn_ms": round(t_dcn, 6),
                "comm_bound_tier": "dcn" if t_dcn > t_ici else "ici",
            }
        # Optimizer-apply analytic pricing (one-pass vs two-pass HBM
        # bytes) rides the cost_model record when the engine runs the
        # fused apply family.
        if isinstance(cost_model.get("optimizer_apply"), dict):
            roofline["optimizer_apply"] = cost_model["optimizer_apply"]
        floor = cm_step.get("floor_ms")
        p50 = _percentile(walls, 50)
        if floor and p50 > 0:
            roofline["measured_p50_over_floor"] = round(p50 / floor, 3)

    # Goodput: aggregate every settled window. The per-window sum-to-wall
    # identity holds by construction (other is the residual); the real
    # checks are each window's `consistent` flag (no double-attribution)
    # and the aggregated accounted fraction.
    gp_windows = [rep["goodput"] for rep in reports
                  if isinstance(rep.get("goodput"), dict)]
    goodput: Dict[str, Any] = {"available": bool(gp_windows)}
    if gp_windows:
        # Only the ledger's CLOSED bucket set joins the accounted sum —
        # everything else a window carries (`*_bg_s` background wall
        # measured on another thread, sub-figures like
        # `checkpoint_snapshot_s` that are subsets of a bucket, future
        # additions) is reported-only, and summing it would double-count
        # seconds the ledger deliberately kept apart. An allowlist keeps
        # that exclusion fail-safe for sub-figures added later.
        ledger_buckets = {"useful_compute", "data_stall", "recompile",
                          "overflow_skipped", "checkpoint",
                          "offload_exposed", "other"}

        def _is_bucket(k: str) -> bool:
            return k.endswith("_s") and k[:-2] in ledger_buckets

        all_keys = set().union(*(w.keys() for w in gp_windows))
        bucket_keys = sorted(k for k in all_keys if _is_bucket(k))
        totals = {k: sum(float(w.get(k, 0.0)) for w in gp_windows)
                  for k in bucket_keys}
        total_window = sum(float(w.get("window_s", 0.0)) for w in gp_windows)
        ck_exposed = totals.get("checkpoint_s", 0.0)
        ck_snapshot = sum(float(w.get("checkpoint_snapshot_s", 0.0))
                          for w in gp_windows)
        ck_write_bg = sum(float(w.get("checkpoint_write_bg_s", 0.0))
                          for w in gp_windows)
        goodput.update({
            "windows": len(gp_windows),
            "total_window_s": round(total_window, 6),
            "buckets_s": {k[:-2]: round(v, 6) for k, v in totals.items()},
            "goodput_fraction": round(
                totals.get("useful_compute_s", 0.0) / total_window, 6)
                if total_window > 0 else 0.0,
            "accounted_fraction": round(
                sum(totals.values()) / total_window, 6)
                if total_window > 0 else 1.0,
            "consistent": all(w.get("consistent", False)
                              for w in gp_windows),
        })
        # The resilience split: exposed (paid) checkpoint wall vs the
        # background writer's overlapped wall. exposed_share is what
        # bench_gate's checkpoint gate reads.
        goodput["checkpoint"] = {
            "exposed_s": round(ck_exposed, 6),
            "snapshot_s": round(ck_snapshot, 6),
            "write_bg_s": round(ck_write_bg, 6),
            "exposed_share": round(ck_exposed / total_window, 6)
            if total_window > 0 else 0.0,
        }
        if isinstance(meta.get("checkpoint"), dict):
            goodput["checkpoint"]["snapshot_every"] = \
                meta["checkpoint"].get("snapshot_every")
            goodput["checkpoint"]["async"] = \
                meta["checkpoint"].get("async")

    # Serving: occupancy from the decode-step records, per-request
    # latency percentiles recomputed from the request_complete events
    # (ground truth, not a snapshot), throughput from the last report's
    # aggregator snapshot.
    completions = [e for e in events
                   if e.get("event") == "request_complete"]
    occ = sorted(float(r["occupancy"]) for r in steps
                 if "occupancy" in r)
    serve_snap: Dict[str, Any] = {}
    for rep in reversed(reports):
        if isinstance(rep.get("serving"), dict):
            serve_snap = rep["serving"]
            break
    is_serving = meta.get("mode") == "serving" or bool(occ) or \
        bool(completions)
    serving: Dict[str, Any] = {"available": is_serving}
    if is_serving:
        ttfts = sorted(float(e["ttft_ms"]) for e in completions
                       if "ttft_ms" in e)
        tpots = sorted(float(e["tpot_ms"]) for e in completions
                       if "tpot_ms" in e)
        serving.update({
            "decode_iterations": len(occ),
            "occupancy_mean": round(sum(occ) / len(occ), 4)
            if occ else 0.0,
            "occupancy_p50": round(_percentile(occ, 50), 4),
            "completed": len(completions),
            "ttft_ms": {"p50": round(_percentile(ttfts, 50), 3),
                        "p95": round(_percentile(ttfts, 95), 3),
                        "n": len(ttfts)},
            "tpot_ms": {"p50": round(_percentile(tpots, 50), 3),
                        "p95": round(_percentile(tpots, 95), 3),
                        "n": len(tpots)},
            "tokens_per_s": serve_snap.get("tokens_per_s"),
            "decode_step_ms": serve_snap.get("decode_step_ms"),
            "prefill_tokens": serve_snap.get("prefill_tokens"),
            "decode_tokens": serve_snap.get("decode_tokens"),
        })
        # Queue-wait vs service-TTFT split, recomputed from the
        # request_complete events (ground truth): queue_wait is router/
        # scheduler hold time before admission, service_ttft is
        # admission→first-token — they sum to ttft exactly, so a TTFT
        # regression is attributable to queuing vs prefill at a glance.
        qws = sorted(float(e["queue_wait_ms"]) for e in completions
                     if "queue_wait_ms" in e)
        svc = sorted(float(e["service_ttft_ms"]) for e in completions
                     if "service_ttft_ms" in e)
        if qws:
            serving["queue_wait_ms"] = {
                "p50": round(_percentile(qws, 50), 3),
                "p95": round(_percentile(qws, 95), 3),
                "n": len(qws)}
        if svc:
            serving["service_ttft_ms"] = {
                "p50": round(_percentile(svc, 50), 3),
                "p95": round(_percentile(svc, 95), 3),
                "n": len(svc)}
        # Paged-cache / spec-decode / attend-work / admission sections
        # of the aggregator snapshot pass through when present
        # (pre-paging streams carry none; ``attend`` is the analytic
        # kernel-vs-one-hot pricing, projection-labeled at the source).
        for sec in ("hbm_bytes_per_token", "prefix", "spec", "replica",
                    "attend", "attend_work_ratio", "admission"):
            if serve_snap.get(sec) is not None:
                serving[sec] = serve_snap[sec]
        # Multi-replica streams: request_complete events carry replica
        # labels — split the per-request percentiles per replica so two
        # replicas' latency distributions never interleave into one
        # misleading stream (the pooled figures above remain the honest
        # aggregate).
        labels = sorted({str(e["replica"]) for e in completions
                         if e.get("replica") is not None})
        if len(labels) > 1 or (labels and serving.get("replica")
                               not in (None, labels[0])):
            per_rep: Dict[str, Any] = {}
            for lab in labels:
                evs = [e for e in completions
                       if str(e.get("replica")) == lab]
                tt = sorted(float(e["ttft_ms"]) for e in evs
                            if "ttft_ms" in e)
                tp = sorted(float(e["tpot_ms"]) for e in evs
                            if "tpot_ms" in e)
                per_rep[lab] = {
                    "completed": len(evs),
                    "ttft_ms": {"p50": round(_percentile(tt, 50), 3),
                                "p95": round(_percentile(tt, 95), 3),
                                "n": len(tt)},
                    "tpot_ms": {"p50": round(_percentile(tp, 50), 3),
                                "p95": round(_percentile(tp, 95), 3),
                                "n": len(tp)},
                }
            serving["replicas"] = per_rep

    # Serving SLO / goodput-ledger section — everything re-validates
    # from the JSONL alone:
    # - per-replica wall-time ledger (prefill/decode_useful/spec_wasted/
    #   admission_blocked/idle buckets summing to the serve wall;
    #   `consistent` false means double-attribution),
    # - SLO attainment + burn rate per replica (burn > 1 = the error
    #   budget is being spent faster than the window allows),
    # - worst-TTFT request exemplars with their FULL span timelines
    #   from the `request_trace` events, plus a contiguity audit over
    #   every recorded timeline (gaps/overlaps = instrumentation bugs).
    # Zero completed requests is a reported condition (`slo: null` with
    # the reason), never a crash — a saturated/aborted stream still gets
    # its ledger and traces summarized.
    traces = [e for e in events if e.get("event") == "request_trace"]
    led_by_rep: Dict[str, Any] = {}
    slo_by_rep: Dict[str, Any] = {}
    for rep in reports:
        s = rep.get("serving")
        if not isinstance(s, dict):
            continue
        lab = str(s.get("replica") or "default")
        if isinstance(s.get("ledger"), dict):
            led_by_rep[lab] = s["ledger"]
        if isinstance(s.get("slo"), dict):
            slo_by_rep[lab] = s["slo"]
    serving_slo: Dict[str, Any] = {
        "available": bool(is_serving
                          and (led_by_rep or slo_by_rep or traces))}
    if serving_slo["available"]:
        if led_by_rep:
            serving_slo["ledger"] = {
                "replicas": led_by_rep,
                "consistent": all(bool(l.get("consistent"))
                                  for l in led_by_rep.values()),
            }
        if not completions:
            serving_slo["slo"] = None
            serving_slo["slo_unavailable_reason"] = \
                "no completed requests in this segment"
        elif slo_by_rep:
            burn: Dict[str, Any] = {}
            for lab, s in slo_by_rep.items():
                br = s.get("burn_rate")
                burn[lab] = {
                    "attainment": s.get("attainment"),
                    "burn_rate": br,
                    "verdict": ("no_target" if br is None
                                else "burning" if br > 1.0 else "ok"),
                }
            serving_slo["slo"] = {"replicas": slo_by_rep, "burn": burn}
        else:
            serving_slo["slo"] = None
            serving_slo["slo_unavailable_reason"] = \
                "no slo targets configured (inference.slo unset)"
        if traces:
            def _tl_errors(tl: Dict[str, Any]) -> int:
                """Gap/overlap count: spans must tile [0, total_ms]
                exactly (shared endpoints by construction)."""
                spans = tl.get("spans") or []
                errs = 0 if spans else 1
                cur = 0.0
                for sp in spans:
                    if abs(float(sp.get("t_ms", 0.0)) - cur) > 1e-6:
                        errs += 1
                    cur = float(sp.get("t_ms", 0.0)) + \
                        float(sp.get("dur_ms", 0.0))
                if spans and abs(cur - float(tl.get("total_ms", 0.0))) \
                        > 1e-6:
                    errs += 1
                return errs

            keep = ("rid", "outcome", "replica", "spans", "total_ms",
                    "ttft_ms", "queue_wait_ms", "service_ttft_ms",
                    "admission_attempts", "new_tokens", "route",
                    "abort_reason")
            done = sorted(
                (e for e in traces if e.get("ttft_ms") is not None),
                key=lambda e: -float(e["ttft_ms"]))
            serving_slo["traces"] = {
                "recorded": len(traces),
                "completed": sum(1 for e in traces
                                 if e.get("outcome") == "complete"),
                "aborted": sum(1 for e in traces
                               if e.get("outcome") == "abort"),
                "contiguity_violations": sum(
                    1 for e in traces if _tl_errors(e)),
                "worst_ttft": [
                    {k: e[k] for k in keep if k in e}
                    for e in done[:3]],
            }

    # Truncation: a marker-capable segment without the terminal `final`
    # record died mid-run — its partial-window stats must not read as a
    # complete run. Pre-marker streams: unknown (None), never a false
    # verdict.
    truncated: Optional[bool] = (not saw_final) \
        if meta.get("emits_final") else None
    if truncated:
        goodput["truncated"] = True
        mfu["truncated"] = True

    # Health: anomaly/watchdog events, flight-recorder presence, and
    # the multi-host shard aggregation.
    anomalies = [e for e in events if e.get("event") == "anomaly"]
    watchdogs = [e for e in events if e.get("event") == "watchdog"]
    counts: Dict[str, int] = {}
    nonfinite = 0
    nonfinite_unskipped = 0
    for a in anomalies:
        k = str(a.get("anomaly", "unknown"))
        counts[k] = counts.get(k, 0) + 1
        if k.startswith("nonfinite"):
            nonfinite += 1
            # Overflow-SKIPPED steps are routine fp16 loss-scale
            # mechanics (update discarded); a non-finite value that was
            # NOT skipped entered the params/loss — the defect class.
            if not a.get("overflow"):
                nonfinite_unskipped += 1
    flight: Dict[str, Any] = {"present": False}
    stream_dir = os.path.dirname(os.path.abspath(jsonl_path))
    candidates = []
    meta_fp = meta.get("flight_path")
    if meta_fp:
        # The recorded path may be relative to the RUN's cwd, not ours;
        # fall back to the same basename next to the analyzed stream.
        # No meta flight_path = this segment never armed a recorder —
        # do NOT glob for an artifact, or a previous run's crash file
        # in the same directory gets attributed to a clean run.
        candidates.append(meta_fp)
        candidates.append(os.path.join(stream_dir,
                                       os.path.basename(meta_fp)))
    fpath = next((c for c in candidates if os.path.exists(c)), None)
    if fpath:
        flight = {"present": True, "path": fpath}
        try:
            with open(fpath) as f:
                fdoc = json.load(f)
            flight.update({"reason": fdoc.get("reason"),
                           "closed_clean": fdoc.get("closed_clean"),
                           "last_steps": len(fdoc.get("last_steps") or []),
                           "watchdog_fires": fdoc.get("watchdog_fires")})
        except (OSError, json.JSONDecodeError):
            flight["parse_error"] = True
    hosts = aggregate_hosts(jsonl_path, meta, steps, saw_final)
    health: Dict[str, Any] = {
        "available": bool(meta.get("health_enabled")) or bool(anomalies)
        or bool(watchdogs),
        "anomalies": {
            "total": len(anomalies),
            "nonfinite": nonfinite,
            "nonfinite_unskipped": nonfinite_unskipped,
            "counts": counts,
            # `anomaly_step` is the step the anomaly happened AT;
            # the record's `step` field is the drain-time counter and
            # would mislabel every anomaly in a window with the report
            # boundary's step.
            "events": [dict(
                {k: a.get(k) for k in
                 ("anomaly", "first_nonfinite_leaf",
                  "first_nonfinite_layer", "overflow", "metric", "z")
                 if k in a},
                step=a.get("anomaly_step", a.get("step")))
                for a in anomalies[:8]],
        },
        "watchdog_fires": len(watchdogs),
        "flight_recorder": flight,
        "truncated": truncated,
        "hosts": hosts,
    }

    offload_steps = [r["offload"] for r in steps
                     if isinstance(r.get("offload"), dict)]
    offload: Optional[Dict[str, Any]] = None
    if offload_steps:
        fracs = [float(o.get("overlap_fraction", 0.0))
                 for o in offload_steps]
        offload = {
            "steps": len(offload_steps),
            "overlap_fraction_mean": round(sum(fracs) / len(fracs), 4),
            "num_buckets": offload_steps[-1].get("num_buckets"),
            "overlapped": offload_steps[-1].get("overlapped"),
        }

    # Profile: the measured half of the roofline story — capture-window
    # outcomes (structured profile_window events), the bucketed per-step
    # wall decomposition from the ingested jax.profiler trace, and the
    # reconciliation verdict + divergences against the analytic floors.
    windows = [e for e in events if e.get("event") == "profile_window"]
    prof_events = [e for e in events if e.get("event") == "profile"]
    div_events = [e for e in events
                  if e.get("event") == "reconcile_divergence"]
    profile: Dict[str, Any] = {"available": bool(prof_events)}
    if windows:
        profile["windows"] = [
            {k: w.get(k) for k in ("phase", "path", "start_step",
                                   "stop_step", "ok", "reason") if k in w}
            for w in windows]
    if prof_events:
        last = prof_events[-1]
        d = last.get("decomposition") or {}
        r = last.get("reconciliation") or {}
        profile.update({
            "steps": d.get("steps"),
            "per_step_wall_ms": d.get("per_step_wall_ms"),
            "per_step_ms": d.get("per_step_ms"),
            "sum_check": d.get("sum_check"),
            "pallas_families_ms": d.get("pallas_families_ms"),
            "n_device_ops": d.get("n_device_ops"),
        })
        if last.get("error"):
            profile["error"] = last["error"]
        if r:
            profile["reconciliation"] = {
                "verdict": r.get("verdict"),
                "dominant_bucket": r.get("dominant_bucket"),
                "predicted_bound": r.get("predicted_bound"),
                "components": r.get("components"),
                "paths": r.get("paths"),
            }
    if div_events:
        profile["divergences"] = [
            {k: e.get(k) for k in ("component", "measured_ms", "floor_ms",
                                   "measured_over_floor", "wall_frac",
                                   "threshold", "step") if k in e}
            for e in div_events]

    return {
        "source": os.path.basename(jsonl_path),
        "meta": {k: v for k, v in meta.items() if k not in ("kind", "ts")},
        "steps_recorded": len(steps),
        "dropped_records": sum(int(rep.get("dropped_records", 0))
                               for rep in reports),
        "step_time_ms": {
            "p50": round(_percentile(walls, 50), 3),
            "p95": round(_percentile(walls, 95), 3),
            "mean": round(sum(walls) / len(walls), 3) if walls else 0.0,
            "n": len(walls),
            "note": "host wall per train_batch: dispatch wall on jitted "
                    "paths, true wall on the host-synchronous offload path",
        },
        "throughput": {
            "samples_per_sec": samples_per_sec,
            "window_valid": samples_per_sec is not None,
        },
        "recompiles": {
            "count": len(recompiles),
            "events": [{"fn": e.get("fn"),
                        "step": e.get("step"),
                        "signature_delta": e.get("signature_delta")}
                       for e in recompiles],
        },
        "memory": memory,
        "wire_bytes_per_step": wire_meta,
        "wire_bytes_consistent": wire_consistent,
        "overflow_steps": overflows,
        "skipped_steps": skipped,
        "offload": offload,
        "mfu": mfu,
        "roofline": roofline,
        "goodput": goodput,
        "serving": serving,
        "serving_slo": serving_slo,
        "moe": moe,
        "health": health,
        "profile": profile,
        "truncated": truncated,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="telemetry JSONL stream to summarize")
    ap.add_argument("-o", "--output", default="TELEMETRY.json",
                    help="summary output path (default TELEMETRY.json)")
    args = ap.parse_args(argv)
    summary = summarize(args.jsonl)
    with open(args.output, "w") as f:
        json.dump(summary, f, indent=2)
    st = summary["step_time_ms"]
    mfu = summary["mfu"].get("window_mfu") or \
        summary["mfu"].get("per_step_p50")
    gp = summary["goodput"].get("goodput_fraction")
    ck = summary["goodput"].get("checkpoint")
    ck_share = ck["exposed_share"] if isinstance(ck, dict) and \
        ck.get("exposed_s", 0) > 0 else None
    bound = summary["roofline"].get("step_bound")
    srv = summary["serving"]
    hl = summary["health"]
    health_bits = ""
    if hl.get("available"):
        health_bits = (f", anomalies={hl['anomalies']['total']}, "
                       f"watchdog={hl['watchdog_fires']}")
        if hl["hosts"].get("available"):
            health_bits += (f", hosts={hl['hosts']['n_hosts']} "
                            f"(skew={hl['hosts'].get('straggler_skew_rel')})")
    print(f"{args.output}: {summary['steps_recorded']} steps, "
          f"p50={st['p50']}ms p95={st['p95']}ms, "
          f"recompiles={summary['recompiles']['count']}, "
          f"watermarks={summary['memory']['watermark_events']}"
          + (f", mfu={mfu}" if mfu is not None else "")
          + (f", {bound}-bound" if bound else "")
          + (f", goodput={gp:.1%}" if gp is not None else "")
          + (f", ckpt exposed={ck_share:.2%}"
             if ck_share is not None else "")
          + (f", serving: occ={srv['occupancy_mean']}, "
             f"ttft p50={srv['ttft_ms']['p50']}ms"
             if srv.get("available") else "")
          + (f", attend x{srv['attend_work_ratio']} "
             f"({srv['attend']['mode']}, projected)"
             if srv.get("attend_work_ratio") is not None else "")
          + (", slo=" + ",".join(
              f"{lab}:{b['verdict']}" for lab, b in
              summary["serving_slo"]["slo"]["burn"].items())
             if summary["serving_slo"].get("slo") else "")
          + health_bits
          + ((lambda p: f", profiled: {p['reconciliation']['verdict']} "
              f"(dominant={p['reconciliation']['dominant_bucket']}, "
              f"predicted={p['reconciliation']['predicted_bound']})"
              if p.get("reconciliation") else ", profiled")(
                  summary["profile"])
             if summary["profile"].get("available") else "")
          + (" — TRUNCATED segment (no final drain marker): stats "
             "cover a partial run" if summary["truncated"] else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
