#!/usr/bin/env bash
# Communication audit — compiles the flagship configs on the virtual
# 8-device mesh, checks compiled collectives against the analytic wire
# models, and records COMM_AUDIT.json (mirrors tools/run_tier1.sh).
# Exit 0 = every config's lowering matches its model.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python tools/comm_audit.py "$@"
