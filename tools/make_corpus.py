#!/usr/bin/env python
"""Vendor a small license-clean REAL-TEXT corpus for the e2e examples.

VERDICT.md's top gap: every end-to-end example trained on synthetic
random tokens, so the loss-curve gates never saw real language. This
script assembles a few hundred KB of genuine English prose from the
RUNNING interpreter's standard-library documentation strings — text
written by humans, shipped under the PSF-2.0 license (redistributable
with attribution), and available offline in any Python install, so the
corpus can be regenerated without network egress.

Output: ``examples/data/corpus.txt`` (UTF-8; byte-level tokenization is
the intended consumption — see ``examples/gpt2/train.py --data *.txt``).
The vendored copy is checked in so tests are deterministic across
Python versions; re-running this script on a different interpreter
produces a different (equally valid) corpus.
"""
from __future__ import annotations

import importlib
import inspect
import io
import os
import re
import sys

# Prose-heavy stdlib modules: tutorial-grade docstrings, not symbol
# soup. Order is deterministic.
MODULES = [
    "argparse", "asyncio", "base64", "bisect", "calendar", "codecs",
    "collections", "concurrent.futures", "configparser", "contextlib",
    "copy", "csv", "datetime", "decimal", "difflib", "dis", "doctest",
    "email", "enum", "fileinput", "fractions", "functools", "gettext",
    "glob", "gzip", "hashlib", "heapq", "hmac", "html", "http.client",
    "imaplib", "inspect", "ipaddress", "itertools", "json", "locale",
    "logging", "lzma", "mailbox", "math", "mimetypes", "multiprocessing",
    "netrc", "nntplib", "numbers", "os", "pathlib", "pdb", "pickle",
    "pickletools", "pkgutil", "platform", "plistlib", "poplib", "pprint",
    "profile", "pstats", "queue", "random", "re", "sched", "secrets",
    "selectors", "shelve", "shlex", "shutil", "signal", "smtplib",
    "socket", "socketserver", "sqlite3", "ssl", "statistics", "string",
    "struct", "subprocess", "tarfile", "tempfile", "textwrap",
    "threading", "timeit", "tokenize", "trace", "traceback", "turtle",
    "types", "typing", "unittest", "urllib.parse", "urllib.request",
    "uuid", "warnings", "wave", "weakref", "webbrowser", "xml.dom",
    "xml.etree.ElementTree", "zipfile", "zlib",
]

TARGET_BYTES = 400_000


def _clean(doc: str) -> str:
    doc = inspect.cleandoc(doc)
    # Strip doctest blocks and signature-only lines: keep prose.
    lines = [l for l in doc.splitlines()
             if not l.lstrip().startswith((">>>", "..."))]
    text = "\n".join(lines).strip()
    text = re.sub(r"\n{3,}", "\n\n", text)
    return text


def collect(target: int = TARGET_BYTES) -> str:
    out = io.StringIO()
    seen = set()
    for name in MODULES:
        try:
            mod = importlib.import_module(name)
        except Exception:
            continue
        docs = []
        if mod.__doc__:
            docs.append(mod.__doc__)
        for _, obj in sorted(vars(mod).items()):
            if not (inspect.isclass(obj) or inspect.isfunction(obj)):
                continue
            if getattr(obj, "__module__", None) != mod.__name__:
                continue
            d = inspect.getdoc(obj)
            if d and len(d) > 120:
                docs.append(d)
        for d in docs:
            t = _clean(d)
            if len(t) < 80 or t in seen:
                continue
            seen.add(t)
            out.write(t)
            out.write("\n\n")
        if out.tell() >= target:
            break
    return out.getvalue()[:target]


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_dir = os.path.join(repo, "examples", "data")
    os.makedirs(out_dir, exist_ok=True)
    text = collect()
    path = os.path.join(out_dir, "corpus.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {path}: {len(text.encode('utf-8'))} bytes "
          f"(python {sys.version.split()[0]} stdlib docstrings, PSF-2.0)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
