#!/usr/bin/env python
"""Compile-time lint sweep over the flagship configs — records
LINT_AUDIT.json and gates CI on unwaived findings.

Builds each flagship engine on the virtual 8-device CPU mesh, runs two
toy steps (so every compiled path registers with the recompile
sentinel), then runs the analysis/ lint suite over the registry:
materialization, dtype_flow, donation, host_sync, collective_placement.
The audit itself is host-side AOT re-lowering — the tool asserts it
issued ZERO device fences via the instrumented ``device_sync_count``
counter and records the delta in the artifact.

Flagships (the engine modes whose compiled programs differ):

- **zero1**   — stage 1, fused Adam (sharded moments, replicated grads)
- **zero2**   — stage 2, grad_sync auto (explicit reduce-scatter here)
- **zero3**   — stage 3, fp16: params born dp-sharded, the prefetched
  per-layer gather scan on gpt2-tiny; materialization gates declared
  state + bounded gather working set, never the full fp32 master tree
- **onebit**  — 1-bit Adam compression step (stage 0 shard_map psums)
- **offload** — ZeRO-Offload bucketed grad pass (host Adam)
- **pipeline_1f1b** — compiled pp=2 interleaved pipeline ticks
- **moe**    — expert-parallel MoE FFN (8 experts top-2, ep=4 x dp=2,
  ZeRO-2): all-to-all dispatch/combine, expert weights born sharded
  over the `expert` axis; since the factored explicit grad path landed,
  dense grads reduce-scatter over `data` (the old stage-2 declarative
  regression, closed) and collective_placement's expert check gates
  that no expert grad all-reduces across the expert axis
- **multislice** — hierarchical ICI/DCN gradient sync on the
  slices=2 x dp=4 mesh (ZeRO-2, gas=2): grads reduce-scatter in-slice
  INSIDE the gas scan, only the 1/dp residual all-reduces across
  slices, and collective_placement's slice check gates that nothing
  grad-sized spans the slice axis (a flat joint sync over DCN)
- **zero3_multislice** — ZeRO-3 across slices (slices=2 x dp=4,
  gas=2) via the axis-algebra planner: params born dp-sharded within
  each slice, every param gather binds `data` (ICI only), one
  residual all-reduce across slices; collective_placement gates both
  grad-spans-dcn and the param-spans-dcn check (a param-sized gather
  over the joint (slice, data) group)
- **serving** — the inference tier's paged compiled paths (gpt2-tiny,
  continuous batching over the block pool): group-batched chunked
  prefill, plain decode, the speculative verify step, and the
  copy-on-write block copy; the serving contract is host_sync and
  materialization CLEAN: no full-pool gather through the block-table
  one-hot contractions under the blocks-over-dp sharding, no in-step
  host transfer

Known-and-roadmapped findings live in ``tools/lint_waivers.json`` —
every waiver must match a live finding (stale waivers fail ``--check``),
and any NEW finding fails it too.

Usage:
    python tools/ds_lint.py [--out LINT_AUDIT.json]
                            [--waivers tools/lint_waivers.json]
                            [--check]            # exit 1 on unwaived/stale
                            [--configs zero2 offload ...]

CI: ``tools/run_tier1.sh --lint`` (or LINT_GATE=1) runs ``--check``.
"""
import argparse
import json
import os
import sys
import tempfile

# The 8-device virtual mesh, exactly like tests/conftest.py — must be set
# before jax initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                     # noqa: E402
import jax.numpy as jnp        # noqa: E402
import numpy as np             # noqa: E402

jax.config.update("jax_platforms", "cpu")

import deepspeed_tpu           # noqa: E402
from deepspeed_tpu.analysis.findings import (apply_waivers,  # noqa: E402
                                             load_waivers)
from deepspeed_tpu.utils import timer as timer_mod  # noqa: E402


# ------------------------------------------------------------------ #
# Tiny fixture model (mirror of tests/simple_model.py, kept local so the
# tool runs without the test tree on path)
# ------------------------------------------------------------------ #
def _params(seed=0, dim=8, hidden=16, classes=4):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {"w1": jax.random.normal(k1, (dim, hidden)) * 0.1,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, classes)) * 0.1,
            "b2": jnp.zeros((classes,))}


def _loss_fn(params, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _batch(n=16, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) % classes
    return (x, y)


def _tel(name):
    return {"enabled": True, "output_path": tempfile.mkdtemp(),
            "job_name": f"lint_{name}", "report_steps": 10 ** 9}


def _engine(name, config_overrides, optimizer=None, gas=1):
    cfg = {"train_batch_size": 16 * gas,
           "gradient_accumulation_steps": gas,
           "optimizer": optimizer or {"type": "Adam",
                                      "params": {"lr": 1e-2}},
           "steps_per_print": 10 ** 9,
           "telemetry": _tel(name)}
    cfg.update(config_overrides)
    engine, *_ = deepspeed_tpu.initialize(
        model=_loss_fn, model_params=_params(), config=cfg)
    for i in range(2):
        engine.train_batch(batch=_batch(n=16 * gas, seed=i))
    return engine


# ------------------------------------------------------------------ #
# Flagship engines — each returns a trained-one-window engine whose
# sentinel registry holds every compiled path of that mode.
# ------------------------------------------------------------------ #
def build_zero1():
    return _engine("zero1", {"zero_optimization": {"stage": 1}})


def build_zero2():
    # gas=2 so the in-scan scatter placement is part of the audited
    # program (the collective_placement hoist check is live).
    return _engine("zero2", {"zero_optimization": {"stage": 2}}, gas=2)


def build_zero3():
    # Stage 3 on the stacked-layer model with the prefetched layer scan:
    # params born dp-sharded, per-layer gathers inside the scan, grads
    # reduce-scattered — the materialization pass gates that no compiled
    # path holds more than declared state + the bounded gather working
    # set (never the fp32 master tree). fp16 exercises the in-flight
    # master-shard -> compute-dtype cast on the gather.
    import dataclasses
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.runtime.zero.stage3 import Zero3Scan

    cfg = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"], num_layers=4,
                              dtype=jnp.float16, hidden_dropout=0.0,
                              attn_dropout=0.0, fused_kernels=False)
    spec = Zero3Scan()
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    ds_cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 3, "prefetch_depth": 1},
              "fp16": {"enabled": True},
              "steps_per_print": 10 ** 9, "telemetry": _tel("zero3")}
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, zero3=spec), model_params=params,
        config=ds_cfg, zero3_scan=spec)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(16, 33)).astype(np.int32)
    for _ in range(2):
        engine.train_batch(batch=tokens)
    return engine


def build_onebit():
    return _engine("onebit", {}, optimizer={
        "type": "OneBitAdam", "params": {"lr": 1e-3, "freeze_step": 2}})


def build_offload():
    return _engine("offload", {
        "zero_optimization": {"stage": 2, "cpu_offload": True}})


def build_pipeline_1f1b():
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    from deepspeed_tpu.parallel.topology import build_mesh

    def block(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    L, D = 4, 8
    params = {f"layer_{i}": {
        "w": jax.random.normal(jax.random.PRNGKey(i), (D, D)) * 0.3,
        "b": jnp.zeros((D,))} for i in range(L)}
    module = PipelineModule(
        [block] * L, num_stages=2,
        loss_fn=lambda x, labels: jnp.mean(
            (x.sum(axis=(-1, -2)) - labels) ** 2),
        partition_method="uniform")
    spec = module.to_pipe_spec(params)
    cfg = {"train_batch_size": 4, "train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 2,
           "pipeline": {"schedule": "1f1b"},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10 ** 9,
           "telemetry": _tel("pipeline_1f1b")}
    # pp=2 x dp=1: inside this jax's shard_map capability envelope
    # (pp>1 x dp>1 needs partial-auto — see tests/capability.py).
    mesh = build_mesh(pp=2, devices=jax.devices()[:2])
    engine = PipelineEngine(model=spec, config=cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 4, D)).astype(np.float32)
    for _ in range(2):
        engine.train_batch((x, x.sum(axis=(-1, -2))))
    return engine


def build_moe():
    # MoE expert parallelism: 8-expert top-2 gpt2-tiny on the ep=4 x
    # dp=2 mesh, ZeRO-2. Historically this flagship ran ZeRO-1 because
    # the stage-2 declarative lowering regressed to all-reduce + slice
    # for the (expert, data)-sharded batch; the factored explicit grad
    # path (shard_map over (expert, data), psum_scatter over data +
    # cross-group all-reduce of the dense residual) closed that — the
    # passes now gate the CLOSED state: dense grads reduce-scatter,
    # dispatch/combine stay real all-to-alls with no tree-scale
    # materialization of expert state, and collective_placement's
    # expert check proves no expert grad ever all-reduces ACROSS the
    # expert axis (its seeded violation lives in tests/test_moe.py).
    # grouped_gemm=True runs the expert FFN through the Pallas grouped
    # kernel (interpret-mode here), so materialization/dtype_flow also
    # gate the kernel path: the recompute-not-save VJP must keep the
    # [E,C,F] fp32 pre-activation out of the held residual set.
    import dataclasses
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.moe import MoEConfig, gpt2_moe_param_shardings
    from deepspeed_tpu.parallel.topology import build_mesh

    ep, E = 4, 8
    mesh = build_mesh(ep=ep)
    moe = MoEConfig(num_experts=E, top_k=2, capacity_factor=1.5,
                    expert_parallel_size=ep, grouped_gemm=True)
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=64, max_seq_length=33,
        hidden_dropout=0.0, attn_dropout=0.0, dtype=jnp.float32,
        fused_kernels=False, moe=moe)
    ds_cfg = {"train_batch_size": 32,
              "train_micro_batch_size_per_gpu": 4,
              "gradient_accumulation_steps": 1,
              "zero_optimization": {"stage": 2},
              "gradient_clipping": 1.0,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "moe": {"num_experts": E, "top_k": 2,
                      "capacity_factor": 1.5,
                      "expert_parallel_size": ep,
                      "grouped_gemm": True},
              "steps_per_print": 10 ** 9, "telemetry": _tel("moe")}
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
        config=ds_cfg, mesh=mesh,
        param_shardings=gpt2_moe_param_shardings(cfg))
    rng = np.random.default_rng(0)
    for _ in range(2):
        engine.train_batch(rng.integers(0, 64, size=(32, 34))
                           .astype(np.int32))
    return engine


def build_serving():
    from deepspeed_tpu.inference import (InferenceEngine,
                                         shared_prefix_requests,
                                         synthetic_requests)
    from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init

    cfg = GPT2_CONFIGS["gpt2-tiny"]
    engine = InferenceEngine(
        cfg, gpt2_init(jax.random.PRNGKey(0), cfg),
        config={"inference": {"max_slots": 8, "max_seq_len": 64,
                              "prefill_chunk": 8, "block_size": 8,
                              "spec_k": 3, "paged_kernel": True},
                "telemetry": _tel("serving")})
    # Register every paged compiled path with the sentinel: an exact
    # re-admission forks copy-on-write (copy_block), the shared-prefix
    # serve runs batched chunk prefills + speculative verify steps, and
    # one plain decode covers the non-spec decode path. paged_kernel is
    # forced ON (interpret mode on this CPU mesh) so the audited
    # programs are the Pallas table-sliced attend the TPU runs — a
    # kernel-on engine declares zero one-hot score budget, so a clean
    # materialization pass here IS the proof that kernel decode/verify/
    # prefill never build a pool-sized intermediate. host_sync must
    # still show zero in-step transfers (the one token-fetch per
    # iteration happens outside the programs).
    rng = np.random.default_rng(0)
    p32 = rng.integers(0, cfg.vocab_size, size=32).astype(np.int32)
    for _ in range(2):                      # second pass hits CoW
        tok, _ = engine.prefill(p32, slot=0)
        engine.activate_slot(0, 32, tok)
        engine.release_slot(0)
    assert engine.allocator.cow_copies == 1
    engine.serve(shared_prefix_requests(6, prefix_len=16,
                                        tail_len=(3, 8),
                                        max_new_tokens=4,
                                        vocab_size=cfg.vocab_size))
    tok, _ = engine.prefill(p32[:8], slot=0)
    engine.activate_slot(0, 8, tok)
    engine.decode_once()                    # the non-spec decode path
    engine.release_slot(0)
    return engine


def build_multislice():
    # Multi-slice hierarchical sync: slices=2 x dp=4 ZeRO-2 with gas=2
    # so the audited program carries the full schedule — in-slice
    # psum_scatter INSIDE the accumulation scan, one inter-slice
    # all-reduce of the accumulated 1/dp residual outside it.
    # collective_placement's slice check (grad-spans-dcn) gates that
    # nothing grad-sized crosses the slice axis.
    return _engine("multislice", {"zero_optimization": {"stage": 2},
                                  "mesh": {"slices": 2}}, gas=2)


def build_zero3_multislice():
    # ISSUE 18: ZeRO-3 across slices via the axis-algebra planner —
    # params born dp-sharded within each slice, gathers bind `data`
    # (ICI only), the residual all-reduce is the single inter-slice
    # exchange. collective_placement's slice-tier checks gate BOTH
    # directions: grad-spans-dcn (flat joint grad sync) and the new
    # param-spans-dcn (a param-sized gather whose groups span `slice`
    # — its seeded violation lives in tests/test_multislice.py).
    return _engine("zero3_multislice",
                   {"zero_optimization": {"stage": 3},
                    "mesh": {"slices": 2}}, gas=2)


FLAGSHIPS = {
    "zero1": build_zero1,
    "zero2": build_zero2,
    "zero3": build_zero3,
    "onebit": build_onebit,
    "offload": build_offload,
    "pipeline_1f1b": build_pipeline_1f1b,
    "serving": build_serving,
    "moe": build_moe,
    "multislice": build_multislice,
    "zero3_multislice": build_zero3_multislice,
}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "LINT_AUDIT.json"))
    ap.add_argument("--waivers",
                    default=os.path.join(REPO, "tools", "lint_waivers.json"))
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any unwaived finding or stale waiver")
    ap.add_argument("--configs", nargs="*", default=None,
                    help="subset of flagship configs (default: all)")
    args = ap.parse_args()

    waivers = load_waivers(args.waivers)
    names = args.configs or list(FLAGSHIPS)
    record = {
        "generated_by": "tools/ds_lint.py",
        "mesh": {"devices": jax.device_count(),
                 "backend": jax.devices()[0].platform,
                 "jax": jax.__version__},
        "waiver_file": os.path.relpath(args.waivers, REPO),
        "passes": ["materialization", "dtype_flow", "donation",
                   "host_sync", "collective_placement"],
        "configs": {},
    }
    all_findings = []
    fences = 0
    lint_config = None
    for name in names:
        build = FLAGSHIPS.get(name)
        if build is None:
            print(f"[ds_lint] unknown config {name!r} "
                  f"(have: {', '.join(FLAGSHIPS)})")
            return 2
        print(f"[ds_lint] auditing {name} ...", flush=True)
        try:
            engine = build()
            # Fence accounting brackets ONLY the audit call — the claim
            # is about the AUDIT being pure host work; the engine builds
            # and toy warm-up steps fence freely outside the window.
            t0 = timer_mod.device_sync_count()
            # Waivers are applied globally below (a waiver for another
            # config must not read as stale here).
            report = engine.lint_audit()
            fences += timer_mod.device_sync_count() - t0
            lint_config = report.config
            all_findings.extend(report.findings)
            record["configs"][name] = {
                "paths": [p.name for p in report.paths],
                "findings": [f.to_dict() for f in report.findings],
                "errors": report.errors,
            }
            engine.telemetry.close()
        except Exception as e:   # keep the record whole
            record["configs"][name] = {
                "error": f"{type(e).__name__}: {str(e)[:300]}",
                "findings": [], "errors": [str(e)[:300]]}
    unwaived, waived, stale = apply_waivers(all_findings, waivers)
    # Staleness is only judgeable on the FULL flagship sweep: a waiver
    # for an un-audited config matches nothing here without being stale
    # (the findings.apply_waivers contract). A --configs subset records
    # itself as partial and never fails on staleness.
    full_sweep = set(names) >= set(FLAGSHIPS)
    if not full_sweep:
        stale = []
    record["subset"] = not full_sweep
    for name, cfg_rec in record["configs"].items():
        fps = {f["fingerprint"] for f in cfg_rec.get("findings", [])}
        cfg_rec["unwaived"] = sorted(
            f.fingerprint for f in unwaived if f.fingerprint in fps)
        cfg_rec["pass"] = not cfg_rec["unwaived"] and \
            not cfg_rec.get("errors") and "error" not in cfg_rec
    record["waived"] = [{"finding": f.to_dict(), "waiver": w.to_dict()}
                        for f, w in waived]
    record["stale_waivers"] = [w.to_dict() for w in stale]
    record["audit_device_fences"] = int(fences)
    if lint_config is not None:
        record["lint_config"] = lint_config.to_dict()
    record["all_pass"] = (all(c.get("pass", False)
                              for c in record["configs"].values())
                          and not stale and fences == 0)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: v.get("pass") for k, v in
                      record["configs"].items()}, indent=1))
    print(f"[ds_lint] {len(all_findings)} finding(s): "
          f"{len(unwaived)} unwaived, {len(waived)} waived, "
          f"{len(stale)} stale waiver(s); "
          f"audit device fences: {fences}")
    for f in unwaived:
        print(f"[ds_lint] UNWAIVED {f.fingerprint}: {f.summary}")
    for w in stale:
        print(f"[ds_lint] STALE WAIVER {w.match!r}: matched no finding "
              f"({w.reason})")
    print(f"[ds_lint] wrote {args.out}; all_pass={record['all_pass']}")
    if args.check:
        return 0 if record["all_pass"] else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
