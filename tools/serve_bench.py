#!/usr/bin/env python
"""Serving bench: drive a synthetic open-loop arrival stream through the
InferenceEngine and record SERVE_BENCH.json.

The serving acceptance artifact: batch occupancy, TTFT/TPOT p50/p95,
generated tokens/s, decode-step wall percentiles, and the recompile
count (which must be ZERO post-warmup — the bench runs with
``fail_on_recompile`` armed, so a retrace kills the run rather than
silently polluting the numbers). The engine's telemetry JSONL is
summarized through ``tools/telemetry_report.py`` and its ``serving``
section is embedded verbatim, proving the report pipeline and the bench
agree on the same stream.

Honest methodology note (recorded in the artifact): on the virtual
8-device CPU mesh the ABSOLUTE numbers (tokens/s, TTFT) measure XLA's
CPU backend, not a TPU; what transfers is the structure — occupancy
under continuous batching, the zero-recompile property, and the
relative cost split between prefill and decode. ``tools/bench_gate.py``
diffs serving rounds on these figures.

Usage:
    python tools/serve_bench.py [--model gpt2-tiny] [--slots 8]
        [--requests 24] [--max-new 16] [--chunk 8] [--max-len 128]
        [--rate 0.0] [--quantize none] [--temperature 0.0]
        [--out SERVE_BENCH.json]
"""
import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                     # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np             # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="gpt2-tiny")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--prompt-len", type=int, nargs=2, default=(8, 24))
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate (req/s); 0 = saturation "
                         "(all arrive at t=0)")
    ap.add_argument("--quantize", default="none",
                    choices=("none", "bf16", "int8"))
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(REPO, "SERVE_BENCH.json"))
    args = ap.parse_args()

    from deepspeed_tpu.inference import InferenceEngine, synthetic_requests
    from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init

    cfg = GPT2_CONFIGS[args.model]
    params = gpt2_init(jax.random.PRNGKey(args.seed), cfg)
    tel_dir = tempfile.mkdtemp(prefix="serve_bench_")
    engine = InferenceEngine(cfg, params, config={
        "inference": {"max_slots": args.slots, "max_seq_len": args.max_len,
                      "prefill_chunk": args.chunk,
                      "quantize": args.quantize},
        "telemetry": {"enabled": True, "output_path": tel_dir,
                      "job_name": "serve_bench", "report_steps": 16,
                      "fail_on_recompile": True}})
    requests = synthetic_requests(
        args.requests, prompt_len=tuple(args.prompt_len),
        max_new_tokens=args.max_new, rate_rps=args.rate,
        vocab_size=cfg.vocab_size, seed=args.seed)
    print(f"[serve_bench] {args.model}: {args.requests} requests, "
          f"{args.slots} slots, max_new={args.max_new}, "
          f"chunk={args.chunk}, quantize={args.quantize} ...", flush=True)
    report = engine.serve(requests, temperature=args.temperature)
    engine.close()

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import summarize
    telemetry = summarize(os.path.join(tel_dir, "serve_bench.jsonl"))

    record = {
        "generated_by": "tools/serve_bench.py",
        "mesh": {"devices": jax.device_count(),
                 "backend": jax.devices()[0].platform,
                 "jax": jax.__version__,
                 "dp": engine.dp, "mp": engine.mp},
        "model": args.model,
        "config": {"max_slots": args.slots, "max_seq_len": args.max_len,
                   "prefill_chunk": args.chunk,
                   "quantize": args.quantize, "requests": args.requests,
                   "max_new_tokens": args.max_new,
                   "prompt_len": list(args.prompt_len),
                   "arrival_rate_rps": args.rate,
                   "temperature": args.temperature},
        "serving": {k: v for k, v in report.items() if k != "requests"},
        "telemetry_report_serving": telemetry.get("serving"),
        "honest_note": (
            "virtual 8-device CPU mesh: absolute tokens/s and latency "
            "measure XLA's CPU backend, not a TPU. The transferable "
            "claims are structural — batch occupancy under continuous "
            "batching, zero post-warmup recompiles (fail_on_recompile "
            "was armed for this run), and the prefill/decode cost "
            "split."),
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    s = record["serving"]
    print(f"[serve_bench] wrote {args.out}: occupancy="
          f"{s['occupancy_mean']}, tokens/s={s['tokens_per_s']}, "
          f"ttft p50/p95={s['ttft_ms']['p50']}/{s['ttft_ms']['p95']} ms, "
          f"tpot p50/p95={s['tpot_ms']['p50']}/{s['tpot_ms']['p95']} ms, "
          f"recompiles={s['recompiles']}, completed={s['completed']}")
    if s["recompiles"] or s["unfinished"]:
        print("[serve_bench] FAILED acceptance (recompiles or unfinished "
              "requests)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
