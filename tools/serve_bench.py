#!/usr/bin/env python
"""Serving bench: drive a synthetic open-loop arrival stream through the
serving tier and record SERVE_BENCH.json.

The serving acceptance artifact, PR-12 shape: the default run drives the
SHARED-PREFIX workload (one common system prompt + varying tails — the
traffic paged prefix sharing is built for) through N paged+speculative
``InferenceEngine`` replicas behind the prefix-affinity
``ReplicaRouter``, and ALSO through a single slot-major PR-7-layout
replica on the exact same request stream — so the paging/spec/replica
win is a measured delta, not a claim. Recorded per side: batch
occupancy, TTFT/TPOT p50/p95, generated tokens/s, decode-step wall
percentiles, HBM-bytes-per-cached-token, prefix hit rate, spec-decode
acceptance rate, per-replica aggregator snapshots (labeled — never one
interleaved percentile stream) plus the pooled aggregate, and the
recompile count (ZERO post-warmup — ``fail_on_recompile`` is armed, so
a retrace kills the run rather than silently polluting the numbers).

Honest methodology note (recorded in the artifact): on the virtual
8-device CPU mesh the ABSOLUTE numbers measure XLA's CPU backend, not a
TPU, and emulated replicas interleave their steps on ONE mesh — their
tokens/s and TTFT are a lower bound on disjoint-mesh replicas. What
transfers is the structure — occupancy, zero recompiles, the
prefill/decode split, HBM-per-token, acceptance and hit rates.
``tools/bench_gate.py`` diffs serving rounds on these figures.

PR-17 adds a paged-attention kernel A/B (``kernel_ablation`` in the
artifact): the same reduced stream served with the Pallas kernel forced
on (interpret mode on CPU) and with the one-hot contraction, greedy
token streams asserted bit-identical, plus the analytic attend-work
ratio and a projection-labeled decode-ms estimate. Skip with
``--no-ablation``.

Usage:
    python tools/serve_bench.py [--model gpt2-tiny] [--slots 8]
        [--requests 24] [--max-new 16] [--chunk 8] [--max-len 128]
        [--block-size 16] [--num-blocks 0] [--spec-k 4] [--replicas 2]
        [--workload shared-prefix|random] [--prefix-len 32]
        [--rate 0.0] [--quantize none] [--temperature 0.0]
        [--no-baseline] [--no-ablation] [--ablation-requests 6]
        [--out SERVE_BENCH.json]
"""
import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                     # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np             # noqa: E402


def _requests(args, vocab_size):
    """Regenerated per run (serve mutates request state) — same seed,
    same stream on both sides of the comparison."""
    from deepspeed_tpu.inference import (shared_prefix_requests,
                                         synthetic_requests)
    if args.workload == "shared-prefix":
        return shared_prefix_requests(
            args.requests, prefix_len=args.prefix_len,
            tail_len=tuple(args.tail_len), max_new_tokens=args.max_new,
            rate_rps=args.rate, vocab_size=vocab_size, seed=args.seed)
    return synthetic_requests(
        args.requests, prompt_len=tuple(args.prompt_len),
        max_new_tokens=args.max_new, rate_rps=args.rate,
        vocab_size=vocab_size, seed=args.seed)


def _serve(args, cfg, params, *, replicas, block_size, spec_k, label,
           paged_kernel=None):
    """Build `replicas` engines and run the stream; returns (report,
    telemetry dir of replica 0). ``paged_kernel`` None leaves the
    engine's "auto" gate in charge (off on this CPU mesh); True/False
    force the Pallas path (interpret mode on CPU) / one-hot baseline."""
    from deepspeed_tpu.inference import InferenceEngine, ReplicaRouter

    inf_cfg_extra = {}
    if paged_kernel is not None:
        inf_cfg_extra["paged_kernel"] = paged_kernel
    if args.slo_ttft_ms or args.slo_tpot_ms:
        inf_cfg_extra["slo"] = {"ttft_ms": args.slo_ttft_ms,
                                "tpot_ms": args.slo_tpot_ms,
                                "availability": args.slo_availability}
    tel_dir = tempfile.mkdtemp(prefix=f"serve_bench_{label}_")
    engines = []
    for i in range(replicas):
        engines.append(InferenceEngine(cfg, params, config={
            "inference": {"max_slots": args.slots,
                          "max_seq_len": args.max_len,
                          "prefill_chunk": args.chunk,
                          "block_size": block_size,
                          "num_blocks": args.num_blocks,
                          "spec_k": spec_k,
                          "quantize": args.quantize,
                          "replica": f"r{i}",
                          **inf_cfg_extra},
            "telemetry": {"enabled": True, "output_path": tel_dir,
                          "job_name": f"serve_bench_r{i}",
                          "report_steps": 16,
                          "fail_on_recompile": True}}))
    if args.warmup:
        # Warm every compiled path before the measured stream so TTFT
        # measures serving, not XLA compiles — applied identically to
        # both sides of the comparison. Short random prompts (< one
        # block) leave the prefix cache untouched.
        from deepspeed_tpu.inference import synthetic_requests
        hi = max(4, min(10, args.chunk + 2)) if args.chunk else 10
        warm = synthetic_requests(
            max(2, 2 * replicas), prompt_len=(4, hi),
            max_new_tokens=args.warmup, vocab_size=cfg.vocab_size,
            seed=args.seed + 991)
        ReplicaRouter(engines, temperature=args.temperature).serve(warm)
        for e in engines:
            e.reset_serving_stats()
    router = ReplicaRouter(engines, temperature=args.temperature)
    report = router.serve(_requests(args, cfg.vocab_size))
    for e in engines:
        e.close()
    return report, tel_dir


def _kernel_ablation(args, cfg, params):
    """Paged-attention kernel on/off A/B: the SAME request stream served
    twice on one replica — Pallas kernel forced on (interpret mode on
    this CPU mesh) vs the one-hot contraction baseline — with greedy
    token streams asserted identical before any number is recorded.
    Interpret-mode wall time measures the Pallas interpreter, not a
    TPU, so the recorded decode-ms projection scales the MEASURED
    one-hot decode step by the analytic attend HBM-bytes ratio and is
    labeled as such."""
    ab = argparse.Namespace(**vars(args))
    ab.requests = min(args.requests, args.ablation_requests)
    ab.replicas = 1
    sides = {}
    for name, flag in (("onehot", False), ("kernel", True)):
        print(f"[serve_bench] kernel ablation: {ab.requests} requests, "
              f"paged_kernel={flag} ...", flush=True)
        report, _ = _serve(ab, cfg, params, replicas=1,
                           block_size=args.block_size,
                           spec_k=args.spec_k, label=f"ab_{name}",
                           paged_kernel=flag)
        sides[name] = report
    toks = {name: {r["rid"]: r["tokens"] for r in rep["requests"]}
            for name, rep in sides.items()}
    parity = toks["kernel"] == toks["onehot"]
    if args.temperature == 0.0 and not parity:
        raise SystemExit(
            "[serve_bench] kernel ablation FAILED: greedy token streams "
            "diverge between the Pallas kernel and the one-hot baseline")
    off, on = sides["onehot"], sides["kernel"]
    ratio = off.get("attend_work_ratio")
    off_p50 = off["decode_step_ms"]["p50"]
    rec = {
        "requests": ab.requests,
        "tokens_compared": sum(len(t) for t in toks["onehot"].values()),
        "greedy_parity": bool(parity),
        "attend_work_ratio": ratio,
        "attend": off.get("attend"),
        "recompiles": {"onehot": off["recompiles"],
                       "kernel": on["recompiles"]},
        "decode_step_ms_p50": {
            "onehot": off_p50,
            "kernel_interpret": on["decode_step_ms"]["p50"]},
        "projected_decode_step_ms_p50": round(off_p50 / ratio, 3)
        if ratio else None,
        "projection_note": (
            "projected figure = measured one-hot decode p50 divided by "
            "the analytic attend HBM-bytes ratio; assumes attend-HBM-"
            "bound decode on a real TPU. kernel_interpret wall time "
            "measures the Pallas interpreter on CPU — never compare it "
            "to the one-hot number."),
    }
    return rec


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="gpt2-tiny")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=0,
                    help="pool size; 0 = full provisioning")
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--workload", default="shared-prefix",
                    choices=("shared-prefix", "random"))
    ap.add_argument("--prefix-len", type=int, default=32,
                    help="shared system-prompt length")
    ap.add_argument("--tail-len", type=int, nargs=2, default=(4, 12))
    ap.add_argument("--prompt-len", type=int, nargs=2, default=(8, 24),
                    help="random-workload prompt length range")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate (req/s); 0 = saturation "
                         "(all arrive at t=0)")
    ap.add_argument("--quantize", default="none",
                    choices=("none", "bf16", "int8"))
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--warmup", type=int, default=4,
                    help="warmup tokens per throwaway request before "
                         "the measured stream (0 = cold, PR-7 style)")
    ap.add_argument("--slo-ttft-ms", type=float, default=10000.0,
                    help="TTFT SLO target (ms); CPU-mesh-loose default. "
                         "0 disables the TTFT criterion")
    ap.add_argument("--slo-tpot-ms", type=float, default=1000.0,
                    help="TPOT SLO target (ms); 0 disables")
    ap.add_argument("--slo-availability", type=float, default=0.99,
                    help="target fraction of requests inside SLO")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the slot-major single-replica baseline")
    ap.add_argument("--no-ablation", action="store_true",
                    help="skip the paged-attention kernel on/off A/B")
    ap.add_argument("--ablation-requests", type=int, default=6,
                    help="request cap for the kernel A/B (interpret "
                         "mode is slow on CPU)")
    ap.add_argument("--out", default=os.path.join(REPO, "SERVE_BENCH.json"))
    args = ap.parse_args()

    from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init

    cfg = GPT2_CONFIGS[args.model]
    params = gpt2_init(jax.random.PRNGKey(args.seed), cfg)

    print(f"[serve_bench] {args.model}: {args.requests} requests "
          f"({args.workload}), {args.replicas} replica(s) x {args.slots} "
          f"slots, paged bs={args.block_size}, spec_k={args.spec_k}, "
          f"max_new={args.max_new}, chunk={args.chunk}, "
          f"quantize={args.quantize} ...", flush=True)
    report, tel_dir = _serve(args, cfg, params, replicas=args.replicas,
                             block_size=args.block_size,
                             spec_k=args.spec_k, label="paged")

    baseline = None
    if not args.no_baseline:
        print("[serve_bench] slot-major single-replica baseline on the "
              "same stream ...", flush=True)
        baseline, _ = _serve(args, cfg, params, replicas=1, block_size=0,
                             spec_k=0, label="slotmajor")

    ablation = None
    if not args.no_ablation and args.block_size:
        ablation = _kernel_ablation(args, cfg, params)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import summarize
    telemetry = summarize(os.path.join(tel_dir, "serve_bench_r0.jsonl"))

    serving = {k: v for k, v in report.items()
               if k not in ("requests", "replicas", "router")}
    record = {
        "generated_by": "tools/serve_bench.py",
        "mesh": {"devices": jax.device_count(),
                 "backend": jax.devices()[0].platform,
                 "jax": jax.__version__},
        "model": args.model,
        "config": {"max_slots": args.slots, "max_seq_len": args.max_len,
                   "prefill_chunk": args.chunk,
                   "block_size": args.block_size,
                   "num_blocks": args.num_blocks,
                   "spec_k": args.spec_k, "replicas": args.replicas,
                   "workload": args.workload,
                   "prefix_len": args.prefix_len,
                   "tail_len": list(args.tail_len),
                   "quantize": args.quantize, "requests": args.requests,
                   "max_new_tokens": args.max_new,
                   "prompt_len": list(args.prompt_len),
                   "arrival_rate_rps": args.rate,
                   "temperature": args.temperature,
                   "slo": {"ttft_ms": args.slo_ttft_ms,
                           "tpot_ms": args.slo_tpot_ms,
                           "availability": args.slo_availability}},
        "serving": serving,
        "replicas": report.get("replicas"),
        "router": report.get("router"),
        "telemetry_report_serving": telemetry.get("serving"),
        "telemetry_report_serving_slo": telemetry.get("serving_slo"),
        "honest_note": (
            "virtual 8-device CPU mesh: absolute tokens/s and latency "
            "measure XLA's CPU backend, not a TPU, and emulated "
            "replicas interleave on ONE mesh (a lower bound on "
            "disjoint-mesh replicas). The transferable claims are "
            "structural — occupancy under continuous batching, zero "
            "post-warmup recompiles (fail_on_recompile was armed), the "
            "prefill/decode cost split, HBM-bytes-per-token under "
            "paging, prefix hit rate, and the spec-decode acceptance "
            "rate."),
    }
    if ablation is not None:
        record["kernel_ablation"] = ablation
    if baseline is not None:
        record["baseline_slot_major"] = {
            k: v for k, v in baseline.items()
            if k not in ("requests", "replicas", "router")}
        b, s = record["baseline_slot_major"], serving

        def _ratio(new, old):
            return round(new / old, 4) if old else None

        record["vs_slot_major"] = {
            "ttft_p95_x": _ratio(s["ttft_ms"]["p95"],
                                 b["ttft_ms"]["p95"]),
            "tpot_p50_x": _ratio(s["tpot_ms"]["p50"],
                                 b["tpot_ms"]["p50"]),
            "tokens_per_s_x": _ratio(s["tokens_per_s"],
                                     b["tokens_per_s"]),
            "hbm_bytes_per_token_x": _ratio(
                s.get("hbm_bytes_per_token", {}).get("p50", 0),
                b.get("hbm_bytes_per_token", {}).get("p50", 0)),
        }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    s = record["serving"]
    print(f"[serve_bench] wrote {args.out}: occupancy="
          f"{s['occupancy_mean']}, tokens/s={s['tokens_per_s']}, "
          f"ttft p50/p95={s['ttft_ms']['p50']}/{s['ttft_ms']['p95']} ms, "
          f"tpot p50/p95={s['tpot_ms']['p50']}/{s['tpot_ms']['p95']} ms, "
          f"hbm/token p50="
          f"{s.get('hbm_bytes_per_token', {}).get('p50', 'n/a')}B, "
          f"prefix hit={s.get('prefix', {}).get('hit_rate', 'n/a')}, "
          f"accept={s.get('spec', {}).get('acceptance_rate', 'n/a')}, "
          f"recompiles={s['recompiles']}, completed={s['completed']}")
    if isinstance(s.get("slo"), dict):
        led = s.get("ledger") or {}
        print(f"[serve_bench] slo: attainment={s['slo'].get('attainment')}"
              f", burn={s['slo'].get('burn_rate')}, ledger accounted="
              f"{led.get('accounted_fraction', 'n/a')} "
              f"(consistent={led.get('consistent', 'n/a')})")
    if record.get("vs_slot_major"):
        print(f"[serve_bench] vs slot-major baseline: "
              f"{record['vs_slot_major']}")
    if ablation is not None:
        print(f"[serve_bench] kernel ablation: parity="
              f"{ablation['greedy_parity']} over "
              f"{ablation['tokens_compared']} tokens, attend work x"
              f"{ablation['attend_work_ratio']}, projected decode p50="
              f"{ablation['projected_decode_step_ms_p50']} ms "
              f"(measured one-hot "
              f"{ablation['decode_step_ms_p50']['onehot']} ms)")
    if s["recompiles"] or s["unfinished"]:
        print("[serve_bench] FAILED acceptance (recompiles or unfinished "
              "requests)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
