#!/usr/bin/env python
"""Crash/kill/resume harness: prove preemption-safety end to end.

The loop ISSUE 15 demands, as a standalone tool:

1. **run** (default): train a deterministic job with auto-saves
   (``checkpoint.snapshot_every``), kill the process at a random moment
   (SIGTERM — the preemption handler commits a final checkpoint — and
   SIGKILL — resume falls back to the last auto-save — alternating,
   including kills landing mid-write under a slowed writer), probe that
   ``latest`` names a loadable checkpoint after EVERY kill, auto-resume
   from ``latest``, and finally compare the crashed-and-resumed
   trajectory against an uninterrupted reference run.

   Trajectory-exactness has two honest tiers (docs/tutorials/
   checkpointing.md):
   - same world size: params AND optimizer moments BIT-identical;
   - elastic resume at a DIFFERENT dp world size: identical up to the
     cross-world float reduction-order floor (an uninterrupted dp=8 run
     and an uninterrupted dp=4 run of the same job already differ by
     ~1e-7 — the harness asserts the resumed run sits within the same
     few-ulp bound, i.e. the kill/resume added NOTHING on top of the
     unavoidable reduction-order difference).

2. **bench**: price the async checkpoint path on the dp=8 CPU mesh with
   ``snapshot_every: 50`` on the goodput ledger (steady-state window,
   warmup settled separately), record RESILIENCE_BENCH.json, and fail
   when the checkpoint-EXPOSED share exceeds 5% or steady-state goodput
   drops under 95% — the acceptance headline, gated again by
   tools/bench_gate.py on the recorded artifact.

3. **child** / **probe**: the subprocess bodies (train segment with
   auto-resume; load-latest check).

CI: ``tools/run_tier1.sh --resilience`` (or RESILIENCE_GATE=1) runs
``crashkill.py run --quick`` + ``bench``.

The training job is self-contained (a small MLP; batches derived from
the step index), so the trajectory is a pure function of the step
count — the property that makes "resumed == uninterrupted" a meaningful
equality and lets a resumed process regenerate exactly the batches the
killed one saw.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Sized so a step's device compute dominates the fixed per-step host
# overhead on the CPU mesh — the goodput measurement then reflects the
# checkpoint subsystem, not Python loop noise — while a full state
# snapshot stays in the low-MB range (checkpoints stay fast to kill
# mid-write but non-trivial to serialize).
DIM, HIDDEN, CLASSES = 256, 1024, 16
GLOBAL_BATCH = 256


def _setup_jax():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
            " --xla_force_host_platform_device_count=8"
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    return jax


def _model():
    import jax
    import jax.numpy as jnp

    def loss_fn(params, batch, rng):
        x, y = batch
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": jax.random.normal(k1, (DIM, HIDDEN)) * 0.1,
        "b1": jnp.zeros((HIDDEN,)),
        "w2": jax.random.normal(k2, (HIDDEN, CLASSES)) * 0.1,
        "b2": jnp.zeros((CLASSES,)),
    }
    return loss_fn, params


def batch_for(step: int):
    """The batch is a pure function of the step index — the determinism
    that makes resumed == uninterrupted an equality, not a vibe."""
    import numpy as np
    rng = np.random.default_rng(1000 + step)
    x = rng.normal(size=(GLOBAL_BATCH, DIM)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) % CLASSES
    return (x, y)


def _engine(dp: int, ckdir: str, snapshot_every: int, use_async: bool,
            telemetry_dir: str = ""):
    jax = _setup_jax()
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.topology import build_mesh
    loss_fn, params = _model()
    mesh = build_mesh(devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": GLOBAL_BATCH,
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 2e-2}},
        "steps_per_print": 10 ** 9,
        "checkpoint": {"async": bool(use_async),
                       "snapshot_every": int(snapshot_every),
                       "save_dir": ckdir},
    }
    if telemetry_dir:
        cfg["telemetry"] = {"enabled": True, "output_path": telemetry_dir,
                            "job_name": "crashkill", "report_steps": 1000,
                            "cost_model": False}
    return DeepSpeedEngine(model=loss_fn, model_params=params,
                           config=cfg, mesh=mesh)


def _dump_state(eng, out: str):
    import jax
    import numpy as np
    leaves = jax.tree_util.tree_leaves(
        jax.device_get(eng.state.params)) + jax.tree_util.tree_leaves(
        jax.device_get(eng.state.opt_state))
    np.savez(out, *[np.asarray(x) for x in leaves])


# --------------------------------------------------------------------- #
# Subprocess bodies
# --------------------------------------------------------------------- #
def cmd_child(args) -> int:
    jax = _setup_jax()
    os.makedirs(args.dir, exist_ok=True)
    eng = _engine(args.dp, args.dir, args.snapshot_every,
                  not args.sync)
    eng.load_checkpoint(args.dir)       # no-op when nothing saved yet
    start = eng.global_steps
    print(f"CRASHKILL_START step={start} dp={args.dp}", flush=True)
    progress = os.path.join(args.dir, "PROGRESS")
    for step in range(start, args.steps):
        eng.train_batch(batch_for(step))
        # Progress beacon for the driver: kills target a STEP, not a
        # wall-clock delay, so they land mid-trajectory on any machine
        # speed (an overwrite, not an append — last completed step).
        with open(progress, "w") as f:
            f.write(str(step + 1))
    if eng._async_ckpt is not None:
        eng._async_ckpt.wait(timeout=120)
    if args.out:
        _dump_state(eng, args.out)
    print(f"CRASHKILL_DONE step={eng.global_steps}", flush=True)
    return 0


def cmd_probe(args) -> int:
    _setup_jax()
    if not os.path.isfile(os.path.join(args.dir, "latest")):
        # A kill can land before the FIRST save: no checkpoint is a
        # valid resume-from-scratch state, not a torn one.
        print("PROBE_EMPTY: no latest yet (resume starts fresh)")
        return 0
    eng = _engine(args.dp, args.dir, 0, False)
    path, _ = eng.load_checkpoint(args.dir)
    if path is None:
        print("PROBE_FAIL: latest names no loadable checkpoint")
        return 3
    print(f"PROBE_OK step={eng.global_steps} path={path}", flush=True)
    return 0


def _spawn(mode: str, ckdir: str, dp: int, steps: int, every: int,
           out: str = "", sync: bool = False, env_extra=None):
    cmd = [sys.executable, os.path.abspath(__file__), mode,
           "--dir", ckdir, "--dp", str(dp), "--steps", str(steps),
           "--snapshot-every", str(every)]
    if out:
        cmd += ["--out", out]
    if sync:
        cmd += ["--sync"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
            " --xla_force_host_platform_device_count=8"
    env.update(env_extra or {})
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)


# --------------------------------------------------------------------- #
# The harness driver
# --------------------------------------------------------------------- #
def _kill_cycle(ckdir: str, dps, steps: int, every: int, kills: int,
                rng: random.Random, slow_write: bool) -> int:
    """Kill/resume until the job completes; returns the number of kills
    actually landed. Asserts a loadable latest after every kill."""
    landed = 0
    attempt = 0
    while True:
        dp = dps[attempt % len(dps)]
        env_extra = {}
        if slow_write and landed % 2 == 1:
            # Every other cycle slows the background writer so the kill
            # lands MID-WRITE with high probability.
            env_extra["DS_CKPT_TEST_WRITE_DELAY_S"] = "0.3"
        marker = os.path.join(ckdir, "PROGRESS")
        start_step = 0
        if os.path.exists(marker):
            start_step = int(open(marker).read() or 0)
            os.remove(marker)
        p = _spawn("child", ckdir, dp, steps, every, env_extra=env_extra)
        if landed >= kills:
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0 or "CRASHKILL_DONE" not in out:
                print(out[-3000:])
                raise SystemExit(
                    f"final (unkilled) run failed rc={p.returncode}")
            print(f"  completing run: dp={dp} rc=0")
            return landed
        sig = signal.SIGTERM if landed % 2 == 0 else signal.SIGKILL
        # Target a STEP somewhere in the remaining trajectory (never the
        # final stretch — the kill must beat completion even if the
        # driver polls slowly), then strike as soon as the child's
        # progress beacon reaches it.
        lo = start_step + 2
        hi = max(lo + 1, int(steps * 0.85))
        target = rng.randint(lo, hi)
        t0 = time.time()
        while p.poll() is None and time.time() - t0 < 300:
            try:
                if int(open(marker).read() or 0) >= target:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.01)
        if p.poll() is None:
            p.send_signal(sig)
            p.wait(timeout=120)
            p.stdout.read()
            if p.returncode == -int(sig):
                landed += 1
                print(f"  kill #{landed}: dp={dp} {sig.name} at "
                      f"step>={target} rc={p.returncode}")
            else:
                # The signal raced process exit; accept a clean finish.
                print(f"  kill raced exit: dp={dp} rc={p.returncode}")
        else:
            out = p.stdout.read()
            print(out[-3000:])
            raise SystemExit(
                f"child finished before the step-{target} kill landed "
                f"(rc={p.returncode}) — the harness proved nothing; "
                "increase --steps")
        # The loadable-latest probe — after EVERY kill.
        pr = _spawn("probe", ckdir, dps[0], steps, 0)
        out, _ = pr.communicate(timeout=300)
        if pr.returncode != 0:
            print(out[-3000:])
            raise SystemExit(
                f"PROBE FAILED after kill #{landed}: latest unloadable")
        attempt += 1


def _max_delta(ref_npz: str, got_npz: str) -> float:
    """0.0 iff bit-identical; else the worst absolute leaf delta."""
    import numpy as np
    ref = np.load(ref_npz)
    got = np.load(got_npz)
    assert len(ref.files) == len(got.files)
    worst = 0.0
    for k in ref.files:
        a, b = ref[k], got[k]
        if not np.array_equal(a, b):
            worst = max(worst, float(np.max(np.abs(
                a.astype(np.float64) - b.astype(np.float64)))))
    return worst


def cmd_run(args) -> int:
    rng = random.Random(args.seed)
    work = args.workdir or tempfile.mkdtemp(prefix="crashkill_")
    os.makedirs(work, exist_ok=True)
    steps, every = args.steps, args.snapshot_every
    print(f"crashkill: steps={steps} snapshot_every={every} "
          f"workdir={work}")

    print("reference run (uninterrupted, dp=8):")
    ref_npz = os.path.join(work, "ref.npz")
    p = _spawn("child", os.path.join(work, "ref"), 8, steps, every,
               out=ref_npz)
    out, _ = p.communicate(timeout=600)
    if p.returncode != 0:
        print(out[-3000:])
        raise SystemExit("reference run failed")

    print(f"same-dp kill/resume cycle ({args.kills} kills, dp=8):")
    same_npz = os.path.join(work, "same.npz")
    same_dir = os.path.join(work, "same")
    # The child writes its state dump only on the COMPLETING run.
    _kill_cycle(same_dir, [8], steps, every, args.kills, rng,
                slow_write=True)
    p = _spawn("child", same_dir, 8, steps, every, out=same_npz)
    out, _ = p.communicate(timeout=600)
    if p.returncode != 0:
        # Never compare a stale .npz from an earlier invocation: a
        # failed dump run must fail the harness, not false-PASS it.
        print(out[-3000:])
        raise SystemExit(f"same-dp dump run failed rc={p.returncode}")
    delta = _max_delta(ref_npz, same_npz)
    if delta != 0.0:
        raise SystemExit(
            f"same-dp kill/resume trajectory NOT bit-exact "
            f"(max |delta| = {delta:.3e})")
    print("  same-dp trajectory: BIT-IDENTICAL")

    if not args.no_elastic:
        # Calibrate the cross-world floor HONESTLY: an uninterrupted
        # dp=4 run of the same job differs from the dp=8 reference by
        # pure float reduction-order noise — no checkpointing involved.
        # The elastic kill/resume run must sit within a small multiple
        # of that floor, i.e. the kills added (at most) more of the
        # same noise, not a trajectory error.
        print("cross-world floor run (uninterrupted, dp=4):")
        floor_npz = os.path.join(work, "floor.npz")
        p = _spawn("child", os.path.join(work, "floor"), 4, steps, every,
                   out=floor_npz)
        out, _ = p.communicate(timeout=600)
        if p.returncode != 0:
            print(out[-3000:])
            raise SystemExit("floor run failed")
        floor = _max_delta(ref_npz, floor_npz)
        tol = max(10.0 * floor, args.elastic_atol)
        print(f"  reduction-order floor (dp=8 vs dp=4, no kills): "
              f"{floor:.3e} -> tolerance {tol:.3e}")

        print(f"elastic kill/resume cycle ({args.kills} kills, "
              "dp cycling 8->4->2):")
        el_npz = os.path.join(work, "elastic.npz")
        el_dir = os.path.join(work, "elastic")
        _kill_cycle(el_dir, [8, 4, 2], steps, every, args.kills, rng,
                    slow_write=True)
        p = _spawn("child", el_dir, 8, steps, every, out=el_npz)
        out, _ = p.communicate(timeout=600)
        if p.returncode != 0:
            print(out[-3000:])
            raise SystemExit(f"elastic dump run failed rc={p.returncode}")
        delta = _max_delta(ref_npz, el_npz)
        if delta == 0.0:
            print("  elastic trajectory: BIT-IDENTICAL")
        else:
            print(f"  elastic trajectory: max |delta| = {delta:.3e} "
                  f"(floor-derived tolerance {tol:.3e})")
        if delta > tol:
            raise SystemExit(
                "elastic kill/resume exceeded the reduction-order floor")
    print("crashkill: PASS")
    return 0


# --------------------------------------------------------------------- #
# Goodput pricing
# --------------------------------------------------------------------- #
def cmd_bench(args) -> int:
    jax = _setup_jax()
    work = args.workdir or tempfile.mkdtemp(prefix="crashkill_bench_")
    results = {}
    for mode in ("async", "sync"):
        tdir = os.path.join(work, f"tel_{mode}")
        ckdir = os.path.join(work, f"ck_{mode}")
        eng = _engine(8, ckdir, args.snapshot_every, mode == "async",
                      telemetry_dir=tdir)
        # Pre-generate the measured window's batches: input prep is not
        # the subsystem under test, and the ledger would book it as
        # `other` (the r-probe showed it dominating the residual).
        batches = [batch_for(s) for s in range(args.steps)]
        for b in batches[:10]:        # warmup: compiles + first dispatch
            eng.train_batch(b)
        eng.telemetry.drain()         # settle the warmup window
        for b in batches[10:]:
            eng.train_batch(b)
        # Close the steady-state window AT loop end: the writer flush
        # and the final drain below are bench epilogue, not training
        # wall, and would otherwise pollute the `other` residual.
        eng.telemetry.drain()
        if eng._async_ckpt is not None:
            eng._async_ckpt.wait(timeout=120)
            eng._async_ckpt.close()
        eng.telemetry.drain()         # settle trailing background wall
        summ = eng.telemetry.ledger.summary()
        eng.telemetry.close()
        recs = [json.loads(l) for l in
                open(os.path.join(tdir, "crashkill.jsonl"))]
        gps = [r["goodput"] for r in recs
               if r.get("kind") == "report" and "goodput" in r]
        w = gps[1]                    # the steady-state window
        share = w["checkpoint_s"] / w["window_s"]
        results[mode] = {
            "window_s": w["window_s"],
            "steps": w["steps"],
            "goodput_fraction": round(
                w["useful_compute_s"] / w["window_s"], 6),
            "checkpoint_exposed_s": w["checkpoint_s"],
            "checkpoint_snapshot_s": w.get("checkpoint_snapshot_s", 0.0),
            # Run-total background write wall (a tail write can settle
            # in the epilogue window — the ledger totals catch it).
            "checkpoint_write_bg_s": summ.get(
                "checkpoint_write_bg_s", 0.0),
            "exposed_share": round(share, 6),
        }
        print(f"{mode}: goodput={results[mode]['goodput_fraction']:.4f} "
              f"exposed_share={share:.4%} "
              f"write_bg={results[mode]['checkpoint_write_bg_s']:.4f}s")
    a = results["async"]
    doc = {
        "bench": "resilience",
        "mesh": "dp=8 cpu",
        "checkpoint": {
            "snapshot_every": args.snapshot_every,
            "async": True,
            "exposed_share": a["exposed_share"],
            "exposed_s": a["checkpoint_exposed_s"],
            "snapshot_s": a["checkpoint_snapshot_s"],
            "write_bg_s": a["checkpoint_write_bg_s"],
            "sync_exposed_share": results["sync"]["exposed_share"],
        },
        "goodput": {"goodput_fraction": a["goodput_fraction"],
                    "steady_window_s": a["window_s"],
                    "steps": a["steps"]},
    }
    out = args.out or os.path.join(REPO, "RESILIENCE_BENCH.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {out}")
    rc = 0
    if a["exposed_share"] > 0.05:
        print(f"FAIL: checkpoint-exposed goodput share "
              f"{a['exposed_share']:.4%} > 5%")
        rc = 1
    if a["goodput_fraction"] < 0.95:
        print(f"FAIL: steady-state goodput "
              f"{a['goodput_fraction']:.4%} < 95%")
        rc = 1
    if rc == 0:
        print("resilience bench: PASS "
              f"(goodput {a['goodput_fraction']:.2%}, exposed "
              f"{a['exposed_share']:.4%} at snapshot_every="
              f"{args.snapshot_every})")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="mode")

    def common(p):
        p.add_argument("--dir", default="")
        p.add_argument("--dp", type=int, default=8)
        p.add_argument("--steps", type=int, default=48)
        p.add_argument("--snapshot-every", type=int, default=8)
        p.add_argument("--out", default="")
        p.add_argument("--sync", action="store_true",
                       help="synchronous saves (default: async)")

    common(sub.add_parser("child", help="one training segment "
                          "(auto-resumes from --dir's latest)"))
    common(sub.add_parser("probe", help="assert latest is loadable"))

    pr = sub.add_parser("run", help="the kill/resume harness (default)")
    pr.add_argument("--steps", type=int, default=600)
    pr.add_argument("--snapshot-every", type=int, default=50)
    pr.add_argument("--kills", type=int, default=3)
    pr.add_argument("--seed", type=int, default=0)
    pr.add_argument("--workdir", default="")
    pr.add_argument("--quick", action="store_true",
                    help="CI-sized: fewer kills, shorter job")
    pr.add_argument("--no-elastic", action="store_true")
    pr.add_argument("--elastic-atol", type=float, default=1e-6,
                    help="minimum cross-world tolerance; the effective "
                         "bound is max(10x the measured dp=8-vs-dp=4 "
                         "reduction-order floor, this). Same-dp is "
                         "always bitwise.")

    pb = sub.add_parser("bench", help="goodput pricing -> "
                        "RESILIENCE_BENCH.json")
    pb.add_argument("--steps", type=int, default=160)
    pb.add_argument("--snapshot-every", type=int, default=50)
    pb.add_argument("--workdir", default="")
    pb.add_argument("--out", default="")

    args = ap.parse_args(argv)
    if args.mode == "child":
        return cmd_child(args)
    if args.mode == "probe":
        return cmd_probe(args)
    if args.mode == "bench":
        return cmd_bench(args)
    if args.mode == "run":
        if args.quick:
            args.steps = min(args.steps, 300)
            args.kills = min(args.kills, 2)
        return cmd_run(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
