#!/usr/bin/env python
"""Communication audit over the flagship configs — records COMM_AUDIT.json.

Compiles (never runs) each flagship parallel configuration on the virtual
8-device mesh, walks the compiled HLO for its collectives
(parallel/hlo_audit.py), compares against the analytic per-config wire
model, and records the structured result. This is the machine-checked form
of the repo's central scaling claims:

- **zero1**: optimizer state sharded, grads replicated — grad sync is a
  dense all-reduce (2(n-1)/n · B wire).
- **zero2**: grads born dp-sharded — sync must be reduce-scattered
  ((n-1)/n · B, half the all-reduce wire, grads never materialize
  unpartitioned). Both the declarative (GSPMD) and explicit
  (lax.psum_scatter) lowerings are audited; the engine's grad_sync=auto
  picks whichever is honest on this backend.
- **zero3**: PARAMS born dp-sharded — per step the sharded params
  all-gather for fwd + re-gather for bwd ((n-1)/n · B each, compute-
  dtype wire) and grads reduce-scatter back to the owning shard; the
  layer-scan program keeps its per-layer gathers inside the scan loop
  (prefetched one layer ahead), never a stacked-tensor-sized gather.
- **onebit**: the in-XLA emulation psums full-precision tensors (recorded
  as such); the DCN wire format is packed sign bits + per-chunk scales,
  ~1/32 of dense (ops/onebit.comm_bytes).
- **pipeline_1f1b**: boundary activations/cotangents ride
  collective-permute inside the tick scan — bytes/step = 2 · ticks ·
  boundary, ticks = M + 2(P-1).
- **ring_attention**: K/V chunks rotate by collective-permute — bytes =
  2 · sp · chunk per forward.
- **moe**: expert-parallel MoE FFN — dispatch/combine lower to REAL
  all-to-alls over the `expert` axis (the first non-synthetic producer
  of the family this parser has priced since PR 6), 4 per MoE layer per
  step (fwd pair + backward transposes), each (ep-1)/ep of the [E,C,H]
  dispatch buffer; now audited at ZeRO-2 on the FACTORED explicit grad
  path: dense grads reduce-scatter over `data` + all-reduce their 1/dp
  residual across expert groups (the old stage-2 declarative
  regression — dense grads materializing unpartitioned — is CLOSED and
  gated here), expert grads reduce-scatter within their expert group
  (data) only.
- **multislice**: hierarchical ICI/DCN sync on the slices=2 x dp=4
  mesh — in-slice reduce-scatter (groups of dp, inside the gas scan) +
  ONE inter-slice all-reduce of the accumulated 1/dp residual (groups
  of `slices`); compiled wire within 5% of the two-tier analytic model
  on BOTH tiers, never a grad-sized collective spanning the slice axis,
  and the `dcn_compression` wire format prices the DCN hop >= 8x
  smaller while ICI bytes are unchanged.
- **zero3_multislice**: ZeRO-3 across slices via the axis-algebra
  planner (parallel/axis_algebra.py) — params born dp-sharded WITHIN
  each slice, every param all-gather binds `data` (ICI only, ZERO
  param bytes on DCN), the layer-scan program keeps its per-layer
  gathers inside the scan, and the only inter-slice exchange is the
  1/dp residual all-reduce; both tiers within 5% of the planner-priced
  wire model.

Usage: python tools/comm_audit.py [--out COMM_AUDIT.json]
(tools/run_comm_audit.sh wraps this with the tier-1 env.)
"""
import argparse
import json
import os
import sys

# The 8-device virtual mesh, exactly like tests/conftest.py — must be set
# before jax initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                     # noqa: E402
import jax.numpy as jnp        # noqa: E402
import numpy as np             # noqa: E402

jax.config.update("jax_platforms", "cpu")

import deepspeed_tpu           # noqa: E402
from deepspeed_tpu.parallel import hlo_audit  # noqa: E402
from deepspeed_tpu.parallel.topology import build_mesh  # noqa: E402


# ------------------------------------------------------------------ #
# Tiny fixture model (mirror of tests/simple_model.py, kept local so the
# tool runs without the test tree on path)
# ------------------------------------------------------------------ #
def _params(seed=0, dim=8, hidden=16, classes=4):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {"w1": jax.random.normal(k1, (dim, hidden)) * 0.1,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, classes)) * 0.1,
            "b2": jnp.zeros((classes,))}


def _loss_fn(params, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _batch(n=16, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) % classes
    return (x, y)


def _engine(config_overrides, optimizer=None, gas=1):
    cfg = {"train_batch_size": 16 * gas,
           "gradient_accumulation_steps": gas,
           # fused=False keeps the optimizer apply out of the grad-sync
           # audit (the fused chunked front end has its own collectives,
           # recorded as a finding below).
           "optimizer": optimizer or {
               "type": "Adam", "params": {"lr": 1e-2, "fused": False}},
           "steps_per_print": 10 ** 9}
    cfg.update(config_overrides)
    engine, *_ = deepspeed_tpu.initialize(
        model=_loss_fn, model_params=_params(), config=cfg)
    return engine


def _audit_train_step(engine, gas=1):
    batch = _batch(n=16 * gas)
    mb = engine._stack_micro_batches(batch)
    mb = jax.device_put(mb, engine._batch_sharding(mb, leading_dims=2))
    fn = engine._build_train_step()
    return hlo_audit.audit_jit(fn, engine.state, mb, engine._base_rng)


# ------------------------------------------------------------------ #
# Flagship configs
# ------------------------------------------------------------------ #
def audit_zero1():
    e = _engine({"zero_optimization": {"stage": 1}})
    audit = _audit_train_step(e)
    model = hlo_audit.grad_sync_wire_model(
        jax.device_get(e.state.params), e.dp_size)
    # Stage 1 replicates grads: the sync must be all-reduce, never
    # reduce-scatter. "Present" means an all-reduce at least as big as the
    # LARGEST grad leaf — the always-present 4-byte loss/overflow psums
    # must not satisfy the check (a removed grad sync has to fail it).
    biggest_leaf = max(
        int(np.prod(l.shape)) * 4 for l in
        jax.tree_util.tree_leaves(jax.device_get(e.state.params)))
    ar_grad = [o for o in audit.of_kind("all-reduce")
               if o.payload_bytes >= biggest_leaf]
    checks = {
        "no_reduce_scatter": not audit.of_kind("reduce-scatter"),
        "grad_allreduce_present": bool(ar_grad),
    }
    return {
        "config": {"stage": 1, "dp": e.dp_size, "grad_sync": "n/a"},
        "hlo": audit.summary(),
        "model": {"grad_sync_wire_bytes": model["all_reduce_wire_bytes"],
                  **model},
        "checks": checks, "pass": all(checks.values()),
    }


def audit_zero2():
    out = {"config": {"stage": 2, "dp": 8}}
    results = {}
    for mode in ("declarative", "explicit"):
        e = _engine({"zero_optimization": {"stage": 2, "grad_sync": mode}})
        audit = _audit_train_step(e)
        model = hlo_audit.grad_sync_wire_model(
            jax.device_get(e.state.params), e.dp_size)
        rs = audit.of_kind("reduce-scatter")
        rs_payload = sum(o.payload_bytes for o in rs)
        rs_wire = sum(o.wire_bytes for o in rs)
        results[mode] = {
            "hlo": audit.summary(),
            "reduce_scatter_payload_bytes": rs_payload,
            "reduce_scatter_wire_bytes": rs_wire,
            "model": model,
            "grad_sync_reduce_scattered":
                rs_payload == model["scatterable_bytes"],
        }
    probe = hlo_audit.zero2_grad_sync_lowering(build_mesh(), "data")
    e_auto = _engine({"zero_optimization": {"stage": 2}})
    model = results["explicit"]["model"]
    checks = {
        # The engine's default (auto) path must be reduce-scattered with
        # wire bytes on the analytic model — the tier-1 regression.
        "auto_mode_guarantees_reduce_scatter":
            results[e_auto._grad_sync_mode]["grad_sync_reduce_scattered"],
        "reduce_scatter_wire_is_half_allreduce": abs(
            model["reduce_scatter_wire_bytes"] /
            max(1, model["all_reduce_wire_bytes"]) - 0.5) < 0.02,
        "explicit_lowering_is_reduce_scatter":
            results["explicit"]["grad_sync_reduce_scattered"],
    }
    out.update({
        "declared_sharding_lowers_to": probe,
        "auto_resolves_to": e_auto._grad_sync_mode,
        "paths": results,
        "checks": checks, "pass": all(checks.values()),
    })
    return out


def audit_zero3():
    """Stage 3: params born dp-sharded; per step every sharded param is
    all-gathered for forward, re-gathered for backward (the remat
    schedule — XLA may CSE the pair into one buffer held across
    fwd/bwd, trading the wire back for memory; both are counted
    honestly), and its grad reduce-scattered back to the owning shard.
    Checks: per-gather wire within 5% of the (g-1)/g ring model, grads
    lower to reduce-scatter (never a grad-sized all-reduce), and the
    layer-scan program keeps its per-layer gathers INSIDE the scan loop
    with no stacked-tensor-sized gather anywhere."""
    e = _engine({"zero_optimization": {"stage": 3}})
    audit = _audit_train_step(e)
    model = hlo_audit.grad_sync_wire_model(
        jax.device_get(e.state.params), e.dp_size, zero3=True,
        param_bytes_per_el=4, gas=1, param_specs=e._stage3_specs)
    ag = audit.of_kind("all-gather")
    rs = audit.of_kind("reduce-scatter")
    ag_payload = sum(o.payload_bytes for o in ag)
    ag_wire = sum(o.wire_bytes for o in ag)
    one_gather = hlo_audit.ring_wire_bytes(
        "all-gather", model["param_gather_payload_bytes"], e.dp_size)
    # Compiled gathers per step: 2 per the declared schedule, 1 when XLA
    # CSEs the remat pair (this backend does).
    gathers = round(ag_payload / max(1, model["param_gather_payload_bytes"]))
    rs_payload = sum(o.payload_bytes for o in rs)
    biggest_leaf = max(
        int(np.prod(l.shape)) * 4 for l in
        jax.tree_util.tree_leaves(jax.device_get(e.state.params)))
    grad_ar = [o for o in audit.of_kind("all-reduce")
               if o.payload_bytes >= biggest_leaf]
    checks = {
        "params_born_sharded": "data" in str(
            e.state.params["w1"].sharding.spec),
        "grad_sync_reduce_scattered":
            rs_payload == model["scatterable_bytes"],
        "no_grad_sized_allreduce": not grad_ar,
        "gather_wire_within_5pct_of_model":
            gathers >= 1 and
            abs(ag_wire - gathers * one_gather) <= 0.05 * ag_wire,
    }

    # The stacked-layer model: gathers must sit INSIDE the scan body
    # (one layer at a time, prefetched), never a full stacked tensor.
    import dataclasses
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.runtime.zero.stage3 import Zero3Scan
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], num_layers=4, dtype=jnp.float32,
        hidden_dropout=0.0, attn_dropout=0.0, fused_kernels=False)
    spec = Zero3Scan()
    gp = gpt2_init(jax.random.PRNGKey(0), cfg)
    ge, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, zero3=spec), model_params=gp,
        config={"train_batch_size": 16, "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3, "prefetch_depth": 1},
                "steps_per_print": 10 ** 9},
        zero3_scan=spec)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          size=(16, 33)).astype(np.int32)
    mb = ge._stack_micro_batches(tokens)
    mb = jax.device_put(mb, ge._batch_sharding(mb, leading_dims=2))
    gaudit = hlo_audit.audit_jit(ge._build_train_step(), ge.state, mb,
                                 ge._base_rng)
    gag = gaudit.of_kind("all-gather")
    stacked_full = {n: int(np.prod(l.shape)) * 4
                    for n, l in gp["blocks"].items()}
    biggest_stacked = max(stacked_full.values())
    scan_checks = {
        "layer_gathers_inside_scan":
            any(o.in_loop for o in gag),
        "no_stacked_tensor_gather":
            all(o.payload_bytes < biggest_stacked for o in gag),
        "grads_reduce_scattered_in_scan":
            any(o.in_loop for o in gaudit.of_kind("reduce-scatter")),
    }
    checks.update({f"scan_{k}": v for k, v in scan_checks.items()})
    return {
        "config": {"stage": 3, "dp": e.dp_size,
                   "grad_sync": e._grad_sync_mode,
                   "prefetch_depth": ge._prefetch_depth},
        "hlo": audit.summary(),
        "model": model,
        "compiled_gather_wire_bytes": ag_wire,
        "compiled_gathers_per_step": gathers,
        "declared_gathers_per_step": model["param_gathers_per_step"],
        "layer_scan_hlo": gaudit.summary(),
        "layer_scan_in_loop_gathers": len([o for o in gag if o.in_loop]),
        "checks": checks, "pass": all(checks.values()),
    }


def audit_onebit():
    from deepspeed_tpu.ops.onebit import comm_bytes
    e = _engine({}, optimizer={
        "type": "OneBitAdam",
        "params": {"lr": 1e-3, "freeze_step": 2}})
    audit = _audit_train_step(e)
    n_el = sum(int(np.prod(l.shape)) for l in
               jax.tree_util.tree_leaves(jax.device_get(e.state.params)))
    dense = comm_bytes(n_el, compressed=False)
    compressed = comm_bytes(n_el, compressed=True, chunks=e.dp_size)
    # The ~1/32 claim is about the wire FORMAT (1 sign bit/element + one
    # f32 scale per chunk) at flagship tensor sizes; the toy engine's
    # 212-element tree amortizes the scales poorly and is recorded as-is.
    flagship_el = 1 << 20
    flagship_ratio = comm_bytes(flagship_el, compressed=False) / \
        comm_bytes(flagship_el, compressed=True, chunks=e.dp_size)
    checks = {
        "flagship_tensor_wire_at_most_1_28th_dense": flagship_ratio >= 28.0,
        # Honest accounting: the in-XLA emulation psums full-precision
        # tensors; the audit must SEE those (compression is a DCN wire
        # format, not an ICI one).
        "emulation_psums_present": bool(audit.of_kind("all-reduce")),
    }
    return {
        "config": {"optimizer": "OnebitAdam", "dp": e.dp_size,
                   "phase": "compression (momentum sign-bits + scales)"},
        "hlo": audit.summary(),
        "hlo_note": "single-program emulation: the compressed exchange is "
                    "psum'd at full precision in-XLA; the wire model below "
                    "is the packed DCN format the 1-bit claims are about "
                    "(ops/onebit.comm_bytes)",
        "model": {"elements": n_el, "dense_wire_bytes_per_rank": dense,
                  "compressed_wire_bytes_per_rank": compressed,
                  "compression_ratio_dense_over_compressed":
                      round(dense / compressed, 2),
                  "flagship_tensor_elements": flagship_el,
                  "flagship_compression_ratio": round(flagship_ratio, 2)},
        "checks": checks, "pass": all(checks.values()),
    }


def _tiny_pipeline(P=8, M=4, mb=2, H=16, S=4, V=32, dp=1):
    """Minimal synthetic pipeline for the 1F1B permute-bytes audit:
    boundary activation is [mb, S, H] f32."""
    from deepspeed_tpu.runtime.pipe.spmd_1f1b import spmd_pipeline_1f1b_grads
    mesh = build_mesh(pp=P, dp=dp,
                      devices=jax.devices()[:P * dp])
    k = jax.random.PRNGKey(0)
    params = {
        "shared": {"wte": jax.random.normal(k, (V, H)) * 0.1},
        "blocks": {"w": jax.random.normal(k, (P, H, H)) * 0.1},
    }

    def embed_fn(shared, tokens, rng):
        return shared["wte"][tokens]

    def stage_fn(blocks, x, rng):
        return jnp.tanh(x @ blocks["w"][0])

    def head_fn(shared, y, targets, rng):
        logits = y @ shared["wte"].T
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(targets, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))

    gfn = spmd_pipeline_1f1b_grads(embed_fn, stage_fn, head_fn,
                                   num_stages=P, num_micro_batches=M,
                                   mesh=mesh)
    batch = jnp.zeros((M * mb * dp, S + 1), jnp.int32)
    boundary_bytes = mb * S * H * 4          # [mb, S, H] f32 per dp rank
    return gfn, params, batch, mesh, boundary_bytes


def _tiny_pipeline_pp_dp(P=4, M=4, dp=2):
    return _tiny_pipeline(P=P, M=M, dp=dp)


def audit_1f1b():
    P, M = 8, 4
    gfn, params, batch, mesh, boundary = _tiny_pipeline(P=P, M=M)
    with mesh:
        audit = hlo_audit.audit_jit(
            jax.jit(gfn), params, batch, jax.random.PRNGKey(1))
    ticks = M + 2 * (P - 1)
    loop_perms = audit.in_loops("collective-permute")
    checks = {
        # one activation rotate up + one cotangent rotate down per tick
        "two_boundary_permutes_per_tick": len(loop_perms) == 2,
        "permute_payload_is_boundary": all(
            o.out_bytes == boundary for o in loop_perms),
        # the COMPILED scan bound equals the schedule oracle's tick count
        # (permute bytes/step = 2 x boundary x ticks then follows from
        # the two payload checks above)
        "compiled_trip_count_matches_tick_table":
            ticks in audit.while_trip_counts(),
    }
    # ZeRO-1 composition: pp x dp is a partially-manual shard_map (manual
    # pipe axis + auto dp axis) — old jax cannot compile it; record the
    # capability honestly instead of asserting by design.
    try:
        gfn_pd, p2, b2, mesh_pd, _ = _tiny_pipeline_pp_dp(P=4, M=M, dp=2)
        with mesh_pd:
            jax.jit(gfn_pd).lower(p2, b2, jax.random.PRNGKey(1)).compile()
        zero1_composition = "compiles on this jax (extend the audit)"
    except NotImplementedError as e:
        zero1_composition = f"capability-gated: {e}"
    except Exception as e:   # pragma: no cover
        zero1_composition = f"{type(e).__name__}: {str(e)[:160]}"
    return {
        "config": {"schedule": "1f1b", "pp": P, "micro_batches": M,
                   "ticks": ticks, "boundary_bytes": boundary},
        "hlo": audit.summary(),
        "model": {"permute_bytes_per_step": 2 * boundary * ticks,
                  "formula": "2 directions x boundary x (M + 2(P-1))"},
        "zero1_composition_pp_x_dp": zero1_composition,
        "checks": checks, "pass": all(checks.values()),
    }


def audit_ring_attention():
    from deepspeed_tpu.ops.ring_attention import ring_attention
    sp, B, S, nH, D = 8, 2, 64, 2, 8
    mesh = build_mesh(sp=8, dp=1)
    q = jnp.zeros((B, S, nH, D), jnp.float32)
    with mesh:
        audit = hlo_audit.audit_jit(
            jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh,
                                                   causal=True)),
            q, q, q)
    chunk = B * (S // sp) * nH * D * 4
    loop_perms = audit.in_loops("collective-permute")
    checks = {
        "two_chunk_permutes_per_hop": len(loop_perms) == 2,
        "permute_payload_is_kv_chunk": all(
            o.out_bytes == chunk for o in loop_perms),
    }
    return {
        "config": {"sp": sp, "B": B, "S": S, "heads": nH, "head_dim": D,
                   "kv_chunk_bytes": chunk},
        "hlo": audit.summary(),
        "model": {"permute_bytes_per_forward": 2 * sp * chunk,
                  "formula": "2 tensors (K,V) x sp hops x chunk"},
        "checks": checks, "pass": all(checks.values()),
    }


def audit_moe():
    """MoE expert parallelism: the FIRST real producer of the
    all-to-all family this module's parser has priced synthetically
    since PR 6. An 8-expert top-2 gpt2-tiny on the ep=4 x dp=2 mesh —
    at ZeRO-2 on the FACTORED explicit grad path since the multislice
    round (historically ZeRO-1: the stage-2 declarative lowering
    regressed to all-reduce + slice for the (expert, data)-sharded
    batch; the factored shard_map closed it, and this audit RECORDS the
    closure):

    - dispatch + combine lower to REAL all-to-alls over the 4-member
      expert groups — 4 per MoE layer (fwd pair + their backward
      transposes), each moving exactly the [E, C, H] dispatch buffer;
    - compiled all-to-all wire within 5% of the analytic
      ``moe_alltoall_wire_model`` (exact, in fact: the buffer shape is
      static);
    - DENSE grads reduce-scatter over ``data`` (never materialize
      unpartitioned at full size — the closed regression's signature);
    - expert-weight grads sync over ``data`` WITHIN their expert group
      only (groups never wider than dp) — experts are not replicas;
    - no collective gathers token buffers ACROSS expert groups (the
      all-to-all degenerating to all-gather; gathers over data are the
      legal ZeRO param pattern)."""
    import dataclasses
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.moe import MoEConfig, gpt2_moe_param_shardings

    ep, E, k, cf = 4, 8, 2, 1.5
    mesh = build_mesh(ep=ep)
    moe = MoEConfig(num_experts=E, top_k=k, capacity_factor=cf,
                    expert_parallel_size=ep)
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=64, max_seq_length=33,
        hidden_dropout=0.0, attn_dropout=0.0, dtype=jnp.float32,
        fused_kernels=False, scan_layers=False, moe=moe)
    e, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
        config={"train_batch_size": 32,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3, "fused": False}},
                "moe": {"num_experts": E, "top_k": k,
                        "capacity_factor": cf,
                        "expert_parallel_size": ep},
                "steps_per_print": 10 ** 9},
        mesh=mesh, param_shardings=gpt2_moe_param_shardings(cfg))
    batch = np.random.default_rng(0).integers(
        0, 64, size=(32, 34)).astype(np.int32)
    mb = e._stack_micro_batches(batch)
    mb = jax.device_put(mb, e._batch_sharding(mb, leading_dims=2))
    audit = hlo_audit.audit_jit(e._build_train_step(), e.state, mb,
                                e._base_rng)
    n_moe = cfg.num_layers
    tokens_per_device = (32 // e.replica_size) * 33
    model = hlo_audit.moe_alltoall_wire_model(
        hidden=cfg.hidden_size, num_experts=E, top_k=k,
        capacity_factor=cf, ep=ep, n_moe_layers=n_moe, bytes_per_el=4,
        tokens_per_device=tokens_per_device)
    a2a = audit.of_kind("all-to-all")
    compiled_wire = sum(o.wire_bytes for o in a2a)
    meta = e._lint_path_meta("train_step")
    expert_bytes = set(meta["expert_leaf_bytes"])
    cross_expert_ar = [o for o in audit.of_kind("all-reduce")
                       if o.payload_bytes in expert_bytes
                       and o.group_size > e.dp_size]
    expert_gather = [o for o in audit.of_kind("all-gather")
                     if o.group_size > e.dp_size
                     and o.payload_bytes >= model["dispatch_buffer_bytes"]]
    # The CLOSED stage-2 regression, audited: on the factored explicit
    # path every scatterable grad leaf psum_scatters over `data` — the
    # compiled reduce-scatter payload must equal the analytic
    # scatterable total (dense leaves at full size + expert leaves at
    # their 1/ep local size), and no DIVISIBLE dense leaf may appear as
    # a full-size all-reduce (the regression's signature: the gradient
    # materializing unpartitioned). Shard-size collisions are excluded
    # from the AR check (losing coverage for that leaf, never CI).
    from deepspeed_tpu.moe.sharding import is_expert_spec
    from deepspeed_tpu.runtime.zero.partition import (_layer_dp,
                                                      _leaf_spec,
                                                      spec_dp_dim)
    p_leaves = jax.tree_util.tree_leaves(jax.device_get(e.state.params))
    spec_leaves = jax.tree_util.tree_structure(
        e.state.params).flatten_up_to(e._param_specs)
    rs_expect = 0
    dense_full_div = set()
    shardish = set()
    for l, sp in zip(p_leaves, spec_leaves):
        nbytes = int(np.prod(l.shape)) * 4
        if is_expert_spec(sp):
            local = nbytes // ep
            layered = _layer_dp(sp, l.shape, e.dp_size, "data")
            if spec_dp_dim(layered, "data") is not None:
                rs_expect += local
                shardish.add(local // e.dp_size)
            continue
        spec = _leaf_spec(l.shape, e.dp_size, "data")
        if any(s is not None for s in spec):
            rs_expect += nbytes
            dense_full_div.add(nbytes)
            shardish.add(nbytes // e.dp_size)
    rs_ops = audit.of_kind("reduce-scatter")
    rs_payload = sum(o.payload_bytes for o in rs_ops)
    dense_regression_ar = [
        o for o in audit.of_kind("all-reduce")
        if o.payload_bytes in (dense_full_div - shardish)]
    checks = {
        "alltoall_pair_per_moe_layer": len(a2a) >= 2 * n_moe,
        "fwd_plus_bwd_alltoalls": len(a2a) == 4 * n_moe,
        "alltoall_payload_is_dispatch_buffer": bool(a2a) and all(
            o.payload_bytes == model["dispatch_buffer_bytes"]
            for o in a2a),
        "alltoall_groups_are_expert_axis": bool(a2a) and all(
            o.group_size == ep for o in a2a),
        "wire_within_5pct_of_model": bool(a2a) and abs(
            compiled_wire - model["wire_bytes_per_step"]) <= \
            0.05 * model["wire_bytes_per_step"],
        "no_expert_grad_allreduce_across_experts": not cross_expert_ar,
        "no_cross_group_token_gather": not expert_gather,
        "grad_sync_resolves_explicit": e._grad_sync_mode == "explicit",
        "stage2_dense_grads_reduce_scattered":
            bool(rs_ops) and rs_payload == rs_expect,
        "stage2_regression_closed_no_dense_fullsize_allreduce":
            not dense_regression_ar,
    }
    return {
        "config": {"num_experts": E, "top_k": k, "capacity_factor": cf,
                   "ep": ep, "dp": e.dp_size,
                   "moe_layers": n_moe,
                   "tokens_per_device": tokens_per_device,
                   "zero_stage": 2,
                   "grad_sync": e._grad_sync_mode},
        "regression_note": (
            "historically audited at ZeRO-1: the stage-2 declarative "
            "lowering regressed dense grads to all-reduce + slice on "
            "the (expert, data) mesh; the factored explicit shard_map "
            "path closed it (ROADMAP 4b) — the stage2_* checks gate "
            "the closure"),
        "hlo": audit.summary(),
        "model": model,
        "compiled_alltoall_wire_bytes": compiled_wire,
        "compiled_alltoalls": len(a2a),
        "expert_grad_allreduces": [
            {"payload_bytes": o.payload_bytes, "group_size": o.group_size,
             "num_groups": o.num_groups}
            for o in audit.of_kind("all-reduce")
            if o.payload_bytes in expert_bytes],
        "checks": checks, "pass": all(checks.values()),
    }


def audit_multislice():
    """Hierarchical ICI/DCN gradient sync on the slices=2 x dp=4 mesh
    (ZeRO-2, gas=2 so the scan placement is audited). The tier-1 gate
    of the multislice round:

    - grads reduce-scatter IN-SLICE (groups of dp) INSIDE the gas scan;
    - the inter-slice all-reduce (groups of `slices`) carries only the
      accumulated 1/dp residual, ONCE per step (outside the scan) —
      never a grad-sized flat collective spanning the slice axis;
    - compiled wire within 5% of the two-tier analytic model on BOTH
      tiers (classified by replica-group signature —
      parallel/multislice.classify_two_tier);
    - with ``dcn_compression``, the PRICED DCN bytes drop >= 8x while
      the ICI figure is unchanged (the in-XLA emulation psums the
      decompressed values; the wire format is packed sign bits + per-
      chunk scales, like the onebit flagship's honesty note)."""
    from deepspeed_tpu.parallel.multislice import two_tier_wire_summary

    slices, gas = 2, 2
    e = _engine({"zero_optimization": {"stage": 2},
                 "mesh": {"slices": slices}}, gas=gas)
    dp = e.dp_size
    audit = _audit_train_step(e, gas=gas)
    model = hlo_audit.grad_sync_wire_model(
        jax.device_get(e.state.params), dp, slices=slices)
    # min_payload 1: the toy tree's smallest DCN shards are 4 B — the
    # 5% gate needs them counted; the only sub-64 B extras swept in are
    # the scalar loss psums (a few bytes against a 636 B tier). Static
    # HLO counts: in-loop collectives appear ONCE, so the ICI figure
    # compares against the per-micro-step model term.
    tiers = two_tier_wire_summary(audit.ops, slices, dp,
                                  min_payload_bytes=1)
    rs = audit.of_kind("reduce-scatter")
    rs_payload = sum(o.payload_bytes for o in rs)
    flat = [o for o in audit.ops
            if o.kind in ("all-reduce", "reduce-scatter")
            and o.payload_bytes >= model["scatterable_bytes"] // 8
            and o.group_size > dp]
    dcn_ars = [o for o in audit.of_kind("all-reduce")
               if o.group_size == slices and o.payload_bytes >= 16]

    # The compression variant prices the SAME program's DCN hop in the
    # 1-bit wire format; the compiled ICI collectives must not change.
    ec = _engine({"zero_optimization": {"stage": 2,
                                        "dcn_compression": True},
                  "mesh": {"slices": slices}}, gas=gas)
    audit_c = _audit_train_step(ec, gas=gas)
    tiers_c = two_tier_wire_summary(audit_c.ops, slices, dp,
                                    min_payload_bytes=1)
    model_c = hlo_audit.grad_sync_wire_model(
        jax.device_get(ec.state.params), dp, slices=slices,
        dcn_compression=True)

    checks = {
        "grads_reduce_scatter_in_slice": bool(rs) and all(
            o.group_size == dp for o in rs),
        "in_slice_scatter_inside_gas_scan": bool(rs) and all(
            o.in_loop for o in rs),
        "rs_payload_is_scatterable":
            rs_payload == model["scatterable_bytes"],
        "dcn_hop_once_outside_scan": bool(dcn_ars) and all(
            not o.in_loop for o in dcn_ars),
        "no_grad_sized_collective_spans_slice_axis": not flat,
        # The ICI tier comparison covers the GRAD-SYNC reduce-scatters
        # (what the model prices); the classified tier totals also
        # carry ZeRO's legal param all-gather after the sharded update
        # and are recorded below for the full picture.
        "ici_wire_within_5pct_of_model": abs(
            sum(o.wire_bytes for o in rs) - model["ici_wire_bytes"]) <= \
            0.05 * model["ici_wire_bytes"],
        "dcn_wire_within_5pct_of_model": abs(
            tiers["dcn"] - model["dcn_wire_bytes"]) <= \
            0.05 * model["dcn_wire_bytes"],
        "compression_prices_dcn_8x_down":
            model_c["dcn_wire_bytes"] >=
            8 * model_c["dcn_wire_bytes_compressed"],
        "compression_leaves_ici_unchanged":
            tiers_c["ici"] == tiers["ici"],
    }
    return {
        "config": {"slices": slices, "dp": dp, "gas": gas,
                   "zero_stage": 2, "grad_sync": e._grad_sync_mode},
        "hlo": audit.summary(),
        "model": {k: v for k, v in model.items() if k != "moe"},
        "compiled_two_tier_wire": tiers,
        "compiled_two_tier_wire_compressed": tiers_c,
        "compression": {
            "dcn_wire_bytes_dense": model_c["dcn_wire_bytes"],
            "dcn_wire_bytes_compressed":
                model_c["dcn_wire_bytes_compressed"],
            "ratio": round(model_c["dcn_wire_bytes"] /
                           model_c["dcn_wire_bytes_compressed"], 2),
        },
        "hlo_note": "the DCN 'wire' figures here classify EMULATED "
                    "collectives on the CPU mesh by replica-group "
                    "signature — structural truth (which ops, what "
                    "payloads, which groups), not measured DCN; the "
                    "compression figures are the packed wire format "
                    "(emulation psums decompressed values, like the "
                    "onebit flagship)",
        "checks": checks, "pass": all(checks.values()),
    }


def audit_zero3_multislice():
    """ISSUE 18 flagship: ZeRO-3 across slices via the axis-algebra
    planner. Params are born dp-sharded WITHIN each slice and
    replicated across slices, so every stage-3 param all-gather binds
    `data` — an ICI axis on every factorization — and ZERO param bytes
    cross DCN; grads reduce-scatter in-slice per micro-step and the
    only inter-slice exchange is ONE all-reduce of the accumulated
    1/dp residual. Checks: gathers and scatters bind dp-sized groups
    (on the toy the gas-scan gathers are LICM-hoisted — params are
    loop-invariant across micro-steps — while the layer-scan program
    below keeps its per-layer gathers INSIDE the scan), one
    residual-sized DCN hop outside the scan, no param- or grad-sized
    collective spanning the slice axis, and both tiers within 5% of
    the planner-priced wire model (gather CSE tolerance as in the
    zero3 flagship)."""
    from deepspeed_tpu.parallel.multislice import two_tier_wire_summary

    slices, gas = 2, 2
    e = _engine({"zero_optimization": {"stage": 3},
                 "mesh": {"slices": slices}}, gas=gas)
    dp = e.dp_size
    audit = _audit_train_step(e, gas=gas)
    params = jax.device_get(e.state.params)
    model = hlo_audit.grad_sync_wire_model(
        params, dp, slices=slices, zero3=True, param_bytes_per_el=4,
        param_specs=e._stage3_specs, mesh=e.mesh)

    ag = [o for o in audit.of_kind("all-gather")
          if o.payload_bytes >= 16]
    ag_payload = sum(o.payload_bytes for o in ag)
    ag_wire = sum(o.wire_bytes for o in ag)
    one_gather = hlo_audit.ring_wire_bytes(
        "all-gather", model["param_gather_payload_bytes"], dp)
    gathers = round(ag_payload /
                    max(1, model["param_gather_payload_bytes"]))
    rs = audit.of_kind("reduce-scatter")
    dcn_ars = [o for o in audit.of_kind("all-reduce")
               if o.group_size == slices and o.payload_bytes >= 16]
    shard_sizes = {int(np.prod(l.shape)) // dp * 4
                   for l in jax.tree_util.tree_leaves(params)}
    smallest_leaf = min(int(np.prod(l.shape)) * 4
                        for l in jax.tree_util.tree_leaves(params))
    spanning = [o for o in audit.ops
                if o.kind in ("all-gather", "all-reduce",
                              "reduce-scatter")
                and o.group_size > dp
                and o.payload_bytes >= smallest_leaf]
    tiers = two_tier_wire_summary(audit.ops, slices, dp,
                                  min_payload_bytes=1)
    compiled_ici = sum(o.wire_bytes for o in rs) + ag_wire
    expected_ici = model["reduce_scatter_wire_bytes"] + \
        gathers * one_gather

    # The layer-scan program on the SAME multislice mesh: per-layer
    # params differ per scan step, so the gathers cannot hoist — they
    # must sit inside the scan, still dp-bound, with no joint-axis or
    # stacked-tensor-sized gather anywhere.
    import dataclasses
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.runtime.zero.stage3 import Zero3Scan
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], num_layers=4, dtype=jnp.float32,
        hidden_dropout=0.0, attn_dropout=0.0, fused_kernels=False)
    spec = Zero3Scan()
    gp = gpt2_init(jax.random.PRNGKey(0), cfg)
    ge, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, zero3=spec), model_params=gp,
        config={"train_batch_size": 16,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3, "prefetch_depth": 1},
                "mesh": {"slices": slices},
                "steps_per_print": 10 ** 9},
        zero3_scan=spec)
    gdp = ge.dp_size
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          size=(16, 33)).astype(np.int32)
    mb = ge._stack_micro_batches(tokens)
    mb = jax.device_put(mb, ge._batch_sharding(mb, leading_dims=2))
    gaudit = hlo_audit.audit_jit(ge._build_train_step(), ge.state, mb,
                                 ge._base_rng)
    gag = gaudit.of_kind("all-gather")
    # On this mesh XLA's all-gather combiner merges one layer's leaf
    # gathers into a single padded buffer (~16% padding), so the
    # in-scan gather payload exceeds any single stacked leaf while
    # still being ONE layer. The guarded regression is a gather of the
    # whole stacked tree (num_layers x a layer): threshold at 2x the
    # unpadded stacked total separates the two decisively.
    stacked_total = sum(int(np.prod(l.shape)) * 4
                        for l in gp["blocks"].values())

    checks = {
        "params_born_sharded_in_slice_replicated_across":
            "data" in str(e.state.params["w1"].sharding.spec) and
            "slice" not in str(e.state.params["w1"].sharding.spec),
        "param_gathers_bind_dp_groups_only": bool(ag) and all(
            o.group_size == dp for o in ag),
        "gather_wire_within_5pct_of_model":
            gathers >= 1 and
            abs(ag_wire - gathers * one_gather) <= 0.05 * ag_wire,
        "grads_reduce_scatter_in_slice_in_scan": bool(rs) and all(
            o.group_size == dp and o.in_loop for o in rs),
        "rs_payload_is_scatterable":
            sum(o.payload_bytes for o in rs) ==
            model["scatterable_bytes"],
        "dcn_hop_once_residual_sized_outside_scan":
            bool(dcn_ars) and all(
                not o.in_loop and o.payload_bytes in shard_sizes
                for o in dcn_ars),
        "no_param_or_grad_sized_op_spans_slice_axis": not spanning,
        "zero_param_bytes_on_dcn": model["dcn_param_bytes"] == 0,
        "ici_wire_within_5pct_of_model":
            abs(compiled_ici - expected_ici) <= 0.05 * expected_ici,
        "dcn_wire_within_5pct_of_model": abs(
            tiers["dcn"] - model["dcn_wire_bytes"]) <= \
            0.05 * model["dcn_wire_bytes"],
        "scan_layer_gathers_inside_scan": any(o.in_loop for o in gag),
        "scan_gathers_never_span_slice_axis": all(
            o.group_size <= gdp for o in gag),
        "scan_no_full_stacked_tree_gather": all(
            o.payload_bytes < 2 * stacked_total for o in gag),
        "scan_grads_reduce_scattered_in_scan": any(
            o.in_loop for o in gaudit.of_kind("reduce-scatter")),
    }
    return {
        "config": {"slices": slices, "dp": dp, "gas": gas,
                   "zero_stage": 3, "grad_sync": e._grad_sync_mode,
                   "layer_scan": {"model": "gpt2-tiny", "num_layers": 4,
                                  "dp": gdp, "prefetch_depth":
                                      ge._prefetch_depth}},
        "hlo": audit.summary(),
        "model": {k: v for k, v in model.items() if k != "moe"},
        "collective_plan": model.get("collective_plan"),
        "compiled_two_tier_wire": tiers,
        "compiled_gathers_per_step": gathers,
        "declared_gathers_per_step": model["param_gathers_per_step"],
        "layer_scan_hlo": gaudit.summary(),
        "layer_scan_in_loop_gathers": len([o for o in gag if o.in_loop]),
        "hlo_note": "emulated collectives classified by replica-group "
                    "signature (structural truth, not measured DCN); "
                    "the toy's gas-scan gathers are LICM-hoisted to "
                    "once per step — strictly less wire than the "
                    "declared per-micro-step schedule the model "
                    "prices, and still `data`-bound",
        "checks": checks, "pass": all(checks.values()),
    }


def audit_fused_chunk_finding():
    """Regression guard for a RESOLVED finding: the fused optimizer's
    chunked multi-tensor front end used to concatenate dp-sharded leaves
    end-to-end, which GSPMD assembled by gathering the FULL padded chunk
    onto every device each step.  The V-interleaved shard-local layout
    (ops/fused_update module docstring) keeps every flat buffer
    dp-sharded through the shard_map'd kernels, so NO chunk-sized
    collective may appear — an empty list here is the pass condition,
    and ds_lint's materialization pass gates the same invariant in CI."""
    e = _engine({"zero_optimization": {"stage": 2}},
                optimizer={"type": "Adam",
                           "params": {"lr": 1e-2, "fused": True}})
    audit = _audit_train_step(e)
    big = [o for o in audit.ops if o.payload_bytes >= 2 ** 18]
    return {
        "fused_chunk_gather_collectives": [
            {"kind": o.kind, "shapes": o.out_shapes,
             "payload_bytes": o.payload_bytes, "op_name": o.op_name}
            for o in big],
        "resolved": not big,
        "note": "RESOLVED by the V-interleaved shard-local chunk layout "
                "(ISSUE 8): the fused apply's flat buffers stay "
                "dp-sharded through the shard_map'd kernels; any "
                "collective listed here is a regression",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "COMM_AUDIT.json"))
    args = ap.parse_args()

    record = {
        "generated_by": "tools/comm_audit.py",
        "mesh": {"devices": jax.device_count(),
                 "backend": jax.devices()[0].platform,
                 "jax": jax.__version__},
        "wire_model": "ring: all-reduce 2(g-1)/g*B; reduce-scatter/"
                      "all-gather (g-1)/g*B; permute B",
        "configs": {},
    }
    for name, fn in [("zero1", audit_zero1), ("zero2", audit_zero2),
                     ("zero3", audit_zero3),
                     ("onebit", audit_onebit),
                     ("pipeline_1f1b", audit_1f1b),
                     ("ring_attention", audit_ring_attention),
                     ("moe", audit_moe),
                     ("multislice", audit_multislice),
                     ("zero3_multislice", audit_zero3_multislice)]:
        print(f"[comm_audit] auditing {name} ...", flush=True)
        try:
            record["configs"][name] = fn()
        except Exception as e:   # pragma: no cover - keep the record whole
            record["configs"][name] = {
                "error": f"{type(e).__name__}: {str(e)[:300]}", "pass": False}
    record["findings"] = {"fused_chunk_gather": audit_fused_chunk_finding()}
    record["all_pass"] = all(c.get("pass", False)
                             for c in record["configs"].values()) and \
        record["findings"]["fused_chunk_gather"].get("resolved", False)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: v.get("pass") for k, v in
                      record["configs"].items()}, indent=1))
    print(f"[comm_audit] wrote {args.out}; all_pass={record['all_pass']}")
    return 0 if record["all_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
