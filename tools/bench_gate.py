#!/usr/bin/env python
"""Bench gate: fail CI when MFU or goodput regresses between rounds.

Usage:
    python tools/bench_gate.py                      # latest two BENCH_r*.json
    python tools/bench_gate.py OLD NEW              # explicit files
    python tools/bench_gate.py --mfu-drop 0.10 --goodput-drop 0.05

Accepted file shapes (auto-detected per file):

- a driver round file ``BENCH_r*.json`` (``{"n": .., "parsed": {bench
  record}}``) — MFU comes from the bench record's ``mfu`` field (the
  shared monitor/peaks.py denominator);
- a raw bench record (the JSON line bench.py prints);
- a ``TELEMETRY.json`` from tools/telemetry_report.py — MFU is the
  fenced ``window_mfu`` (per-step p50 as fallback), goodput is the
  ledger's ``goodput_fraction``;
- a ``SERVE_BENCH.json`` from tools/serve_bench.py (or a serving-mode
  TELEMETRY.json) — serving throughput is generated ``tokens_per_s``,
  serving latency is ``ttft_ms.p95``.

Gate semantics: MFU regresses when it drops by more than ``--mfu-drop``
RELATIVE (default 10%); goodput regresses when the fraction drops by
more than ``--goodput-drop`` ABSOLUTE (default 5 points); serving
tokens/s regresses on a relative drop beyond ``--serve-drop`` (default
10%) and TTFT p95 on a relative RISE beyond ``--ttft-rise`` (default
25% — latency percentiles on a CPU mesh are noisy; the gate catches
step changes, not jitter); the fused-kernel ablation speedup (the
``kernels.fused_speedup`` field a DS_BENCH_KERNELS=1 bench or
``ablate_fused_ln.py`` records) regresses on a relative drop beyond
``--kernel-drop`` (default 10%); the autotuned-tile speedup (the
``kernels.tile_speedup`` field ``ablate_autotune.py --record`` writes
— geomean of the per-kernel winner-over-heuristic ratios) regresses on
a relative drop beyond ``--tile-drop`` (default 10%), and pre-autotune
rounds skip, never fail; the ZeRO-3 prefetch overlap fraction
(``zero3.overlap_fraction`` from ablate_zero3_prefetch.py's
ZERO3_BENCH.json) regresses on the same relative threshold. Paged-cache
serving rounds additionally gate ``serving.hbm_bytes_per_token`` (p50;
regression = a relative RISE beyond ``--hbm-rise``, default 15%) and
the spec-decode ``serving.spec.acceptance_rate`` (new side must clear
``--accept-floor``, default 0.05, and must not drop more than
``--serve-drop`` relative vs the old side when both carry it) —
pre-paging/pre-spec rounds skip these, never fail. Paged-attention
rounds gate ``serving.attend_work_ratio`` (the analytic one-hot-over-
kernel attend HBM ratio the engine prices per iteration; regression =
a relative DROP beyond ``--attend-drop``, default 10% — the structural
win shrank); pre-kernel rounds skip, never fail. A TELEMETRY.json carrying a ``health``
SLO rounds (a serving record carrying the ``slo`` tracker snapshot, or
a TELEMETRY.json ``serving_slo`` section) gate the SLO attainment
fraction on an ABSOLUTE drop beyond ``--slo-drop`` (default 0.05), and
validate the serving goodput ledger's ``consistent`` verdict on the
NEW side alone — double-attribution (a wall second charged to two
buckets) is a defect to refuse, not a regression to diff; pre-SLO
rounds skip both, never fail. A TELEMETRY.json carrying a ``health``
section is additionally validated on the NEW side alone: UNSKIPPED
non-finite anomalies (overflow-skipped steps are routine fp16
loss-scale mechanics and do not gate), watchdog fires, or a ``truncated`` stream (a segment that
died without its final drain marker) fail the round — those are not
regressions to diff but defects to refuse. MoE rounds (a ``moe``
section in TELEMETRY.json, or MOE_BENCH.json) gate the drop-fraction
p95 on an ABSOLUTE rise beyond ``--moe-drop-rise`` (default 0.05) —
dropped tokens are silently-skipped compute; pre-MoE rounds skip,
never fail. Multislice rounds (a ``multislice`` record in
MULTISLICE_BENCH.json, or a TELEMETRY.json roofline ``comm_tiers``
section) gate DCN bytes/step on a RELATIVE rise beyond ``--dcn-rise``
(default 10%) — the slow tier is the scale-out ceiling; pre-multislice
rounds skip, never fail. Stage-3-across-slices rounds (a ``zero3``
record with ``dcn_bytes_per_step`` in MULTISLICE_BENCH.json, from
``ablate_multislice.py --zero3``) gate the hierarchical schedule's DCN
bytes/step on the same relative rise, and the DCN *param* bytes/step
against a relative ceiling over the planner's structural 0 — any param
byte leaking onto the slow tier fails; pre-composition rounds skip,
never fail. Resilience rounds (a ``checkpoint`` record in
RESILIENCE_BENCH.json from ``tools/crashkill.py bench``, or a
TELEMETRY.json goodput section carrying a ``checkpoint`` sub-dict with
nonzero exposed wall) gate the checkpoint-EXPOSED goodput share on the
NEW side against an ABSOLUTE ceiling (``--ckpt-share-max``, default 5%
— the ISSUE-15 acceptance bar at ``snapshot_every: 50``); background
write wall overlaps training and is not charged. Pre-resilience rounds
skip, never fail. A metric missing on either
side is skipped with a notice, never a failure — rounds recorded before
this tool (or before the serving tier / health layer) existed have no
such field, and the gate must not retroactively break them. Exit 0 =
pass/skip, 1 = regression, 2 = usage error.

Opt-in from CI: ``tools/run_tier1.sh --bench-gate`` (or BENCH_GATE=1).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, Optional, Tuple


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def extract_metrics(doc: Dict[str, Any]) -> Dict[str, Optional[float]]:
    """{"mfu", "goodput", "serve_tps", "ttft_p95", "kernel_speedup"}
    (None when the file doesn't carry one)."""
    # Driver round file: the bench record rides in "parsed".
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    mfu: Optional[float] = None
    goodput: Optional[float] = None
    serve_tps: Optional[float] = None
    ttft_p95: Optional[float] = None
    kernel_speedup: Optional[float] = None
    zero3_overlap: Optional[float] = None
    # ZERO3_BENCH.json (ablate_zero3_prefetch.py): the analytic fraction
    # of the per-layer gather the depth-1 prefetch hides.
    z3 = doc.get("zero3")
    if isinstance(z3, dict) and z3.get("overlap_fraction") is not None:
        zero3_overlap = float(z3["overlap_fraction"])
    # MULTISLICE_BENCH.json's `zero3` record (ablate_multislice.py
    # --zero3): stage-3-across-slices DCN figures under the planner's
    # hierarchical schedule. Two gated numbers: total DCN bytes/step
    # (regression = RISE, same rule as the stage-2 multislice gate) and
    # the PARAM bytes on DCN — structurally zero under the planner, so
    # the relative ceiling over an old value of 0 is 0 and ANY param
    # byte that leaks onto the slow tier fails the round. Pre-
    # composition rounds carry no record -> skipped, never failed.
    z3_dcn_bytes: Optional[float] = None
    z3_dcn_param: Optional[float] = None
    if isinstance(z3, dict) and z3.get("available", True):
        if z3.get("dcn_bytes_per_step") is not None:
            z3_dcn_bytes = float(z3["dcn_bytes_per_step"])
        if z3.get("dcn_param_bytes_per_step") is not None:
            z3_dcn_param = float(z3["dcn_param_bytes_per_step"])
    # DS_BENCH_KERNELS ablation record: the fused-over-unfused step
    # speedup (bench.py bench_kernels_ablation / ablate_fused_ln.py).
    krn = doc.get("kernels")
    if isinstance(krn, dict) and krn.get("fused_speedup") is not None:
        kernel_speedup = float(krn["fused_speedup"])
    # Autotune ablation record (ablate_autotune.py): geomean step-level
    # speedup of the autotuned tiles over the static heuristics.
    # Pre-autotune rounds carry no field -> skipped, never failed.
    tile_speedup: Optional[float] = None
    if isinstance(krn, dict) and krn.get("tile_speedup") is not None:
        tile_speedup = float(krn["tile_speedup"])
    # TELEMETRY.json shape: structured mfu/goodput sections.
    if isinstance(doc.get("mfu"), dict):
        sec = doc["mfu"]
        v = sec.get("window_mfu", sec.get("per_step_p50"))
        mfu = float(v) if v is not None else None
    elif isinstance(doc.get("mfu"), (int, float)):
        # Bench record shape: flat fraction-of-peak field.
        mfu = float(doc["mfu"])
    if isinstance(doc.get("goodput"), dict):
        v = doc["goodput"].get("goodput_fraction")
        goodput = float(v) if v is not None else None
    # Serving shape: SERVE_BENCH.json's "serving" record, or a
    # serving-mode TELEMETRY.json's "serving" section (same keys).
    hbm_per_token: Optional[float] = None
    accept_rate: Optional[float] = None
    attend_ratio: Optional[float] = None
    srv = doc.get("serving")
    if isinstance(srv, dict) and (srv.get("available", True)):
        v = srv.get("tokens_per_s")
        serve_tps = float(v) if v is not None else None
        ttft = srv.get("ttft_ms")
        if isinstance(ttft, dict) and ttft.get("p95") is not None:
            ttft_p95 = float(ttft["p95"])
        # Paged-cache rounds: HBM held per cached token (regression =
        # RISE) and the spec-decode acceptance rate (regression = drop
        # below the floor or vs the previous round). Pre-paging rounds
        # carry neither -> skipped, never failed.
        hbm = srv.get("hbm_bytes_per_token")
        if isinstance(hbm, dict) and hbm.get("p50") is not None:
            hbm_per_token = float(hbm["p50"])
        spec = srv.get("spec")
        if isinstance(spec, dict) and \
                spec.get("acceptance_rate") is not None:
            accept_rate = float(spec["acceptance_rate"])
        # Paged-attention rounds: the analytic kernel-vs-one-hot
        # attend-work ratio (one-hot pool-capacity HBM bytes over the
        # kernel's live-context bytes, same iterations — regression =
        # DROP: the structural win shrank). Pre-kernel rounds carry no
        # field -> skipped, never failed.
        if srv.get("attend_work_ratio") is not None:
            attend_ratio = float(srv["attend_work_ratio"])
    # Serving SLO shape: SERVE_BENCH.json's serving record carries the
    # pooled SLO tracker snapshot ("slo") and the serving goodput
    # ledger ("ledger"); a TELEMETRY.json carries the same figures in
    # its "serving_slo" section. Gated: SLO attainment (ABSOLUTE drop)
    # plus the ledger `consistent` verdict validated on the NEW side
    # alone (double-attribution is a defect, not a diff). Pre-SLO
    # rounds carry neither -> skipped, never failed.
    slo_attainment: Optional[float] = None
    ledger_consistent: Optional[bool] = None
    if isinstance(srv, dict):
        sslo = srv.get("slo")
        if isinstance(sslo, dict) and sslo.get("attainment") is not None:
            slo_attainment = float(sslo["attainment"])
        sled = srv.get("ledger")
        if isinstance(sled, dict) and "consistent" in sled:
            ledger_consistent = bool(sled["consistent"])
    ssec = doc.get("serving_slo")
    if isinstance(ssec, dict) and ssec.get("available", True):
        tslo = ssec.get("slo")
        if slo_attainment is None and isinstance(tslo, dict):
            atts = [b["attainment"] for b in
                    (tslo.get("burn") or {}).values()
                    if b.get("attainment") is not None]
            if atts:
                slo_attainment = min(float(a) for a in atts)
        tled = ssec.get("ledger")
        if ledger_consistent is None and isinstance(tled, dict) \
                and "consistent" in tled:
            ledger_consistent = bool(tled["consistent"])
    # MoE shape: a TELEMETRY.json `moe` section or an MOE_BENCH.json
    # record — the gated figure is the drop-fraction p95 (regression =
    # an ABSOLUTE rise: dropped tokens are silently-skipped compute).
    # Pre-MoE rounds carry no section -> skipped, never failed.
    moe_drop: Optional[float] = None
    msec = doc.get("moe")
    if isinstance(msec, dict) and msec.get("available", True):
        df = msec.get("drop_fraction")
        if isinstance(df, dict) and df.get("p95") is not None:
            moe_drop = float(df["p95"])
        elif isinstance(df, (int, float)):
            moe_drop = float(df)
    # Multislice shape: MULTISLICE_BENCH.json's `multislice` record, or
    # a TELEMETRY.json roofline's `comm_tiers` section — the gated
    # figure is DCN bytes/step (regression = a RISE: the slow tier is
    # the scale-out ceiling, and a change that silently moves more
    # bytes over DCN eats it). Pre-multislice rounds carry neither ->
    # skipped, never failed.
    dcn_bytes: Optional[float] = None
    msl = doc.get("multislice")
    if isinstance(msl, dict) and msl.get("available", True) and \
            msl.get("dcn_bytes_per_step") is not None:
        dcn_bytes = float(msl["dcn_bytes_per_step"])
    elif isinstance(doc.get("roofline"), dict):
        tiers = doc["roofline"].get("comm_tiers")
        if isinstance(tiers, dict) and \
                tiers.get("wire_bytes_dcn") is not None:
            dcn_bytes = float(tiers["wire_bytes_dcn"])
    # Resilience shape: RESILIENCE_BENCH.json's top-level `checkpoint`
    # record (tools/crashkill.py bench), or a TELEMETRY.json goodput
    # section's `checkpoint` sub-dict — the gated figure is the
    # checkpoint-EXPOSED goodput share (background write wall overlaps
    # and is free). Validated on the NEW side alone against an absolute
    # ceiling; pre-resilience rounds carry neither -> skipped, never
    # failed.
    ckpt_share: Optional[float] = None
    ckpt_every: Optional[int] = None
    cksec = doc.get("checkpoint")
    if not (isinstance(cksec, dict) and
            cksec.get("exposed_share") is not None) and \
            isinstance(doc.get("goodput"), dict):
        cksec = doc["goodput"].get("checkpoint")
    if isinstance(cksec, dict) and cksec.get("exposed_share") is not None \
            and float(cksec.get("exposed_s", 1.0)) > 0.0:
        ckpt_share = float(cksec["exposed_share"])
        if cksec.get("snapshot_every"):
            ckpt_every = int(cksec["snapshot_every"])
    # Health-layer TELEMETRY.json shape: validated (new side only), not
    # diffed. Pre-health rounds carry no section -> None -> skipped.
    health: Optional[Dict[str, Any]] = None
    hl = doc.get("health")
    if isinstance(hl, dict):
        anom = hl.get("anomalies") or {}
        # Gate on UNSKIPPED non-finite events only: overflow-skipped
        # steps are routine fp16 dynamic-loss-scale mechanics (a healthy
        # fp16 round backs its scale off without being a defect).
        health = {
            "truncated": bool(doc.get("truncated")
                              or hl.get("truncated")),
            "watchdog_fires": int(hl.get("watchdog_fires") or 0),
            "nonfinite": int(anom.get("nonfinite_unskipped",
                                      anom.get("nonfinite")) or 0),
        }
    return {"mfu": mfu, "goodput": goodput, "serve_tps": serve_tps,
            "ttft_p95": ttft_p95, "kernel_speedup": kernel_speedup,
            "tile_speedup": tile_speedup,
            "zero3_overlap": zero3_overlap, "health": health,
            "z3_dcn_bytes": z3_dcn_bytes, "z3_dcn_param": z3_dcn_param,
            "hbm_per_token": hbm_per_token, "accept_rate": accept_rate,
            "attend_ratio": attend_ratio,
            "slo_attainment": slo_attainment,
            "ledger_consistent": ledger_consistent,
            "moe_drop": moe_drop, "dcn_bytes": dcn_bytes,
            "ckpt_share": ckpt_share, "ckpt_every": ckpt_every}


# Measurement-label ranks for the trace-truth ratchet (tools/
# tpu_truth.py): "projected" = analytic model only; "cpu-structural" =
# the identical pipeline ran end-to-end on a CPU mesh (structure
# verified, magnitudes not TPU); "measured" = a real TPU trace backs the
# number. Moving DOWN from "measured" is a regression.
LABEL_RANK = {"projected": 0, "cpu-structural": 1, "measured": 2}


def extract_labels(doc: Dict[str, Any]
                   ) -> Optional[Dict[str, Dict[str, Any]]]:
    """{artifact_name: {"label", "reconciled"}} from a TRUTH.json-style
    doc (``artifacts`` map), a bench doc carrying a ``labels`` map, or a
    single-artifact doc with a top-level ``label``. None = the doc
    predates the truth campaign (ratchet skips, never fails)."""
    arts = doc.get("artifacts")
    if not isinstance(arts, dict):
        d = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        arts = d.get("labels")
        if not isinstance(arts, dict):
            if isinstance(d.get("label"), str):
                arts = {str(d.get("artifact", "bench")): d}
            else:
                return None
    out: Dict[str, Dict[str, Any]] = {}
    for name, rec in arts.items():
        if not isinstance(rec, dict) or not isinstance(rec.get("label"),
                                                       str):
            continue
        out[name] = {
            "label": rec["label"],
            "reconciled": isinstance(rec.get("reconciliation"), dict),
        }
    return out or None


def label_ratchet(old_doc: Dict[str, Any], new_doc: Dict[str, Any]
                  ) -> Optional[List[str]]:
    """The measured-stays-measured ratchet. Returns None when either
    side predates the truth campaign (skip); otherwise the list of
    ratchet violations (empty = OK): an artifact labeled ``measured``
    in the old round that is missing, downgraded, or stripped of its
    reconciliation section in the new round."""
    old_labels = extract_labels(old_doc)
    new_labels = extract_labels(new_doc)
    if old_labels is None or new_labels is None:
        return None
    failures: List[str] = []
    for name, o in sorted(old_labels.items()):
        o_rank = LABEL_RANK.get(o["label"], 0)
        n = new_labels.get(name)
        if o_rank >= LABEL_RANK["measured"]:
            if n is None:
                failures.append(
                    f"{name}: measured artifact dropped from the round")
                continue
            n_rank = LABEL_RANK.get(n["label"], 0)
            if n_rank < o_rank:
                failures.append(
                    f"{name}: label regressed measured -> "
                    f"{n['label']!r}")
        if o["reconciled"] and n is not None and not n["reconciled"]:
            failures.append(
                f"{name}: reconciliation section present in the old "
                f"round, dropped in the new")
    return failures


def _round_key(path: str) -> Tuple[int, str]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return (int(m.group(1)) if m else -1, path)


def latest_rounds(directory: str) -> Optional[Tuple[str, str]]:
    """The previous and latest BENCH_r*.json in ``directory`` (round
    number order), or None when fewer than two exist."""
    rounds = sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")),
                    key=_round_key)
    # Driver side files like BENCH_r04_builder.json are not rounds.
    rounds = [p for p in rounds
              if re.fullmatch(r"BENCH_r\d+\.json", os.path.basename(p))]
    if len(rounds) < 2:
        return None
    return rounds[-2], rounds[-1]


def gate(old_path: str, new_path: str, mfu_drop: float,
         goodput_drop: float, serve_drop: float = 0.10,
         ttft_rise: float = 0.25, kernel_drop: float = 0.10,
         hbm_rise: float = 0.15, accept_floor: float = 0.05,
         moe_drop_rise: float = 0.05, dcn_rise: float = 0.10,
         ckpt_share_max: float = 0.05, tile_drop: float = 0.10,
         attend_drop: float = 0.10, slo_drop: float = 0.05) -> int:
    old = extract_metrics(_load(old_path))
    new = extract_metrics(_load(new_path))
    name_old, name_new = os.path.basename(old_path), \
        os.path.basename(new_path)
    rc = 0
    compared = 0

    if old["mfu"] is not None and new["mfu"] is not None:
        compared += 1
        floor = old["mfu"] * (1.0 - mfu_drop)
        verdict = "OK" if new["mfu"] >= floor else "REGRESSION"
        print(f"mfu: {name_old}={old['mfu']:.4g} -> "
              f"{name_new}={new['mfu']:.4g} "
              f"(floor {floor:.4g}, -{mfu_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["mfu"] is None]
        print(f"mfu: skipped (no mfu field in {', '.join(missing)})")

    if old["goodput"] is not None and new["goodput"] is not None:
        compared += 1
        floor = old["goodput"] - goodput_drop
        verdict = "OK" if new["goodput"] >= floor else "REGRESSION"
        print(f"goodput: {name_old}={old['goodput']:.4f} -> "
              f"{name_new}={new['goodput']:.4f} "
              f"(floor {floor:.4f}, -{goodput_drop:.2f} abs): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["goodput"] is None]
        print(f"goodput: skipped (no goodput section in "
              f"{', '.join(missing)})")

    if old["serve_tps"] is not None and new["serve_tps"] is not None:
        compared += 1
        floor = old["serve_tps"] * (1.0 - serve_drop)
        verdict = "OK" if new["serve_tps"] >= floor else "REGRESSION"
        print(f"serving tokens/s: {name_old}={old['serve_tps']:.4g} -> "
              f"{name_new}={new['serve_tps']:.4g} "
              f"(floor {floor:.4g}, -{serve_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-serving rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["serve_tps"] is None]
        print(f"serving tokens/s: skipped (no serving section in "
              f"{', '.join(missing)})")

    if old["ttft_p95"] is not None and new["ttft_p95"] is not None:
        compared += 1
        ceil = old["ttft_p95"] * (1.0 + ttft_rise)
        verdict = "OK" if new["ttft_p95"] <= ceil else "REGRESSION"
        print(f"serving ttft p95: {name_old}={old['ttft_p95']:.4g}ms -> "
              f"{name_new}={new['ttft_p95']:.4g}ms "
              f"(ceiling {ceil:.4g}ms, +{ttft_rise:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["ttft_p95"] is None]
        print(f"serving ttft p95: skipped (no serving section in "
              f"{', '.join(missing)})")

    if old["kernel_speedup"] is not None and \
            new["kernel_speedup"] is not None:
        compared += 1
        floor = old["kernel_speedup"] * (1.0 - kernel_drop)
        verdict = "OK" if new["kernel_speedup"] >= floor else "REGRESSION"
        print(f"kernel fused speedup: {name_old}="
              f"{old['kernel_speedup']:.4g}x -> "
              f"{name_new}={new['kernel_speedup']:.4g}x "
              f"(floor {floor:.4g}x, -{kernel_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-kernel-ablation rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["kernel_speedup"] is None]
        print(f"kernel fused speedup: skipped (no kernels record in "
              f"{', '.join(missing)})")

    if old["tile_speedup"] is not None and \
            new["tile_speedup"] is not None:
        compared += 1
        floor = old["tile_speedup"] * (1.0 - tile_drop)
        verdict = "OK" if new["tile_speedup"] >= floor else "REGRESSION"
        print(f"autotune tile speedup: {name_old}="
              f"{old['tile_speedup']:.4g}x -> "
              f"{name_new}={new['tile_speedup']:.4g}x "
              f"(floor {floor:.4g}x, -{tile_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-autotune rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["tile_speedup"] is None]
        print(f"autotune tile speedup: skipped (no tile record in "
              f"{', '.join(missing)})")

    if old["attend_ratio"] is not None and \
            new["attend_ratio"] is not None:
        compared += 1
        floor = old["attend_ratio"] * (1.0 - attend_drop)
        verdict = "OK" if new["attend_ratio"] >= floor else "REGRESSION"
        print(f"serving attend work ratio: {name_old}="
              f"{old['attend_ratio']:.4g}x -> "
              f"{name_new}={new['attend_ratio']:.4g}x "
              f"(floor {floor:.4g}x, -{attend_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-paged-kernel rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["attend_ratio"] is None]
        print(f"serving attend work ratio: skipped (no attend record in "
              f"{', '.join(missing)})")

    if old["hbm_per_token"] is not None and \
            new["hbm_per_token"] is not None:
        compared += 1
        ceil = old["hbm_per_token"] * (1.0 + hbm_rise)
        verdict = "OK" if new["hbm_per_token"] <= ceil else "REGRESSION"
        print(f"serving hbm bytes/token: {name_old}="
              f"{old['hbm_per_token']:.4g}B -> "
              f"{name_new}={new['hbm_per_token']:.4g}B "
              f"(ceiling {ceil:.4g}B, +{hbm_rise:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-paging rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["hbm_per_token"] is None]
        print(f"serving hbm bytes/token: skipped (no paged-cache "
              f"record in {', '.join(missing)})")

    if new["accept_rate"] is not None:
        compared += 1
        bad = []
        if new["accept_rate"] < accept_floor:
            bad.append(f"below floor {accept_floor:.2f}")
        if old["accept_rate"] is not None:
            drop_floor = old["accept_rate"] * (1.0 - serve_drop)
            if new["accept_rate"] < drop_floor:
                bad.append(f"dropped >{serve_drop:.0%} rel vs "
                           f"{old['accept_rate']:.4g}")
        verdict = "OK" if not bad else "REGRESSION"
        print(f"spec-decode acceptance: {name_new}="
              f"{new['accept_rate']:.4g}"
              + (f" (prev {old['accept_rate']:.4g})"
                 if old["accept_rate"] is not None else "")
              + f": {'; '.join(bad) if bad else 'above floor'}"
              f": {verdict}")
        if bad:
            rc = 1
    else:
        # Pre-spec-decode rounds skip, never fail.
        print(f"spec-decode acceptance: skipped (no spec record in "
              f"{name_new})")

    if old["slo_attainment"] is not None and \
            new["slo_attainment"] is not None:
        compared += 1
        floor = old["slo_attainment"] - slo_drop
        verdict = "OK" if new["slo_attainment"] >= floor else "REGRESSION"
        print(f"serving slo attainment: {name_old}="
              f"{old['slo_attainment']:.4f} -> "
              f"{name_new}={new['slo_attainment']:.4f} "
              f"(floor {floor:.4f}, -{slo_drop:.2f} abs): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-SLO rounds (no inference.slo target configured, or
        # recorded before the SLO tracker existed) skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["slo_attainment"] is None]
        print(f"serving slo attainment: skipped (no slo record in "
              f"{', '.join(missing)} — pre-SLO round)")

    # Serving-ledger consistency: NEW side only (a defect to refuse,
    # not a regression to diff) — `consistent: false` means some wall
    # second was attributed to two buckets at once, and every share the
    # ledger reports is suspect. Pre-ledger rounds skip, never fail.
    if new["ledger_consistent"] is not None:
        compared += 1
        verdict = "OK" if new["ledger_consistent"] else "FAIL"
        print(f"serving ledger consistency: {name_new}: "
              + ("buckets sum to wall (no double-attribution)"
                 if new["ledger_consistent"] else
                 "double-attribution detected (buckets overlap)")
              + f": {verdict}")
        if not new["ledger_consistent"]:
            rc = 1
    else:
        print(f"serving ledger consistency: skipped (no ledger record "
              f"in {name_new} — pre-ledger round)")

    if old["zero3_overlap"] is not None and \
            new["zero3_overlap"] is not None:
        compared += 1
        floor = old["zero3_overlap"] * (1.0 - kernel_drop)
        verdict = "OK" if new["zero3_overlap"] >= floor else "REGRESSION"
        print(f"zero3 prefetch overlap: {name_old}="
              f"{old['zero3_overlap']:.4g} -> "
              f"{name_new}={new['zero3_overlap']:.4g} "
              f"(floor {floor:.4g}, -{kernel_drop:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-ZeRO-3 rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["zero3_overlap"] is None]
        print(f"zero3 prefetch overlap: skipped (no zero3 record in "
              f"{', '.join(missing)})")

    if old["dcn_bytes"] is not None and new["dcn_bytes"] is not None:
        compared += 1
        ceil = old["dcn_bytes"] * (1.0 + dcn_rise)
        verdict = "OK" if new["dcn_bytes"] <= ceil else "REGRESSION"
        print(f"multislice dcn bytes/step: {name_old}="
              f"{old['dcn_bytes']:.4g}B -> "
              f"{name_new}={new['dcn_bytes']:.4g}B "
              f"(ceiling {ceil:.4g}B, +{dcn_rise:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-multislice rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["dcn_bytes"] is None]
        print(f"multislice dcn bytes/step: skipped (no multislice "
              f"record in {', '.join(missing)})")

    if old["z3_dcn_bytes"] is not None and \
            new["z3_dcn_bytes"] is not None:
        compared += 1
        ceil = old["z3_dcn_bytes"] * (1.0 + dcn_rise)
        verdict = "OK" if new["z3_dcn_bytes"] <= ceil else "REGRESSION"
        print(f"zero3 multislice dcn bytes/step: {name_old}="
              f"{old['z3_dcn_bytes']:.4g}B -> "
              f"{name_new}={new['z3_dcn_bytes']:.4g}B "
              f"(ceiling {ceil:.4g}B, +{dcn_rise:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-composition (stage-3 x slices) rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["z3_dcn_bytes"] is None]
        print(f"zero3 multislice dcn bytes/step: skipped (no zero3 "
              f"record in {', '.join(missing)})")

    if old["z3_dcn_param"] is not None and \
            new["z3_dcn_param"] is not None:
        compared += 1
        # Relative ceiling over the planner's structural 0 is 0: a
        # single param byte leaking onto DCN fails the round.
        ceil = old["z3_dcn_param"] * (1.0 + dcn_rise)
        verdict = "OK" if new["z3_dcn_param"] <= ceil else "REGRESSION"
        print(f"zero3 multislice dcn PARAM bytes/step: {name_old}="
              f"{old['z3_dcn_param']:.4g}B -> "
              f"{name_new}={new['z3_dcn_param']:.4g}B "
              f"(ceiling {ceil:.4g}B, +{dcn_rise:.0%} rel): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["z3_dcn_param"] is None]
        print(f"zero3 multislice dcn PARAM bytes/step: skipped (no "
              f"zero3 record in {', '.join(missing)})")

    if old["moe_drop"] is not None and new["moe_drop"] is not None:
        compared += 1
        ceil = old["moe_drop"] + moe_drop_rise
        verdict = "OK" if new["moe_drop"] <= ceil else "REGRESSION"
        print(f"moe drop fraction p95: {name_old}={old['moe_drop']:.4f} "
              f"-> {name_new}={new['moe_drop']:.4f} "
              f"(ceiling {ceil:.4f}, +{moe_drop_rise:.2f} abs): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        # Pre-MoE rounds skip, never fail.
        missing = [n for n, m in ((name_old, old), (name_new, new))
                   if m["moe_drop"] is None]
        print(f"moe drop fraction: skipped (no moe record in "
              f"{', '.join(missing)})")

    # Checkpoint-exposed goodput share: NEW side only, against an
    # ABSOLUTE ceiling — a checkpointing run that pays more than
    # ckpt_share_max of its wall in exposed checkpoint time has lost
    # the async overlap (the resilience subsystem's whole point).
    # Pre-resilience rounds carry no checkpoint record -> skip, never
    # fail.
    if new["ckpt_share"] is not None:
        compared += 1
        cadence = (f" at snapshot_every={new['ckpt_every']}"
                   if new["ckpt_every"] else "")
        verdict = "OK" if new["ckpt_share"] <= ckpt_share_max \
            else "REGRESSION"
        print(f"checkpoint exposed share: {name_new}="
              f"{new['ckpt_share']:.4%}{cadence} "
              f"(ceiling {ckpt_share_max:.0%} abs): {verdict}")
        if verdict != "OK":
            rc = 1
    else:
        print(f"checkpoint exposed share: skipped (no checkpoint "
              f"record in {name_new} — pre-resilience round)")

    # Health validation: NEW side only (defects, not diffs). Pre-health
    # rounds skip, never fail.
    nh = new.get("health")
    if nh is not None:
        compared += 1
        bad = []
        if nh["truncated"]:
            bad.append("stream truncated (no final drain marker)")
        if nh["watchdog_fires"] > 0:
            bad.append(f"{nh['watchdog_fires']} hang-watchdog fire(s)")
        if nh["nonfinite"] > 0:
            bad.append(f"{nh['nonfinite']} unskipped non-finite "
                       f"anomaly event(s)")
        verdict = "OK" if not bad else "FAIL"
        print(f"health: {name_new}: "
              + ("; ".join(bad) if bad else
                 "no non-finite anomalies, no watchdog fires, "
                 "final marker present")
              + f": {verdict}")
        if bad:
            rc = 1
    else:
        print(f"health: skipped (no health section in {name_new} — "
              "pre-health round)")

    # Trace-truth label ratchet: an artifact that earned its "measured"
    # label (a real TPU trace backs the number) must keep it — a round
    # regressing it to "projected"/"cpu-structural", dropping it, or
    # stripping its reconciliation section FAILS. Pre-truth rounds
    # (no labels either side) skip, never fail.
    ratchet = label_ratchet(_load(old_path), _load(new_path))
    if ratchet is None:
        print("label ratchet: skipped (no measurement labels in "
              f"{name_old} and/or {name_new} — pre-truth rounds)")
    else:
        compared += 1
        verdict = "OK" if not ratchet else "REGRESSION"
        print(f"label ratchet: {name_old} -> {name_new}: "
              + ("; ".join(ratchet) if ratchet
                 else "measured labels and reconciliation sections "
                      "preserved")
              + f": {verdict}")
        if ratchet:
            rc = 1

    if compared == 0:
        print("bench_gate: nothing comparable between the two files "
              "(pre-MFU / pre-serving rounds?) — passing")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="OLD NEW (default: latest two BENCH_r*.json)")
    ap.add_argument("--dir", default=".",
                    help="where to glob BENCH_r*.json (default .)")
    ap.add_argument("--mfu-drop", type=float, default=0.10,
                    help="max tolerated RELATIVE MFU drop (default 0.10)")
    ap.add_argument("--goodput-drop", type=float, default=0.05,
                    help="max tolerated ABSOLUTE goodput-fraction drop "
                         "(default 0.05)")
    ap.add_argument("--serve-drop", type=float, default=0.10,
                    help="max tolerated RELATIVE serving tokens/s drop "
                         "(default 0.10)")
    ap.add_argument("--ttft-rise", type=float, default=0.25,
                    help="max tolerated RELATIVE TTFT p95 rise "
                         "(default 0.25)")
    ap.add_argument("--kernel-drop", type=float, default=0.10,
                    help="max tolerated RELATIVE drop of the fused-"
                         "kernel speedup (default 0.10)")
    ap.add_argument("--tile-drop", type=float, default=0.10,
                    help="max tolerated RELATIVE drop of the autotuned-"
                         "tile speedup vs heuristics (default 0.10)")
    ap.add_argument("--attend-drop", type=float, default=0.10,
                    help="max tolerated RELATIVE drop of the serving "
                         "kernel-vs-one-hot attend-work ratio "
                         "(default 0.10)")
    ap.add_argument("--hbm-rise", type=float, default=0.15,
                    help="max tolerated RELATIVE rise of serving HBM "
                         "bytes per cached token (default 0.15)")
    ap.add_argument("--accept-floor", type=float, default=0.05,
                    help="spec-decode acceptance-rate floor on the new "
                         "side (default 0.05)")
    ap.add_argument("--moe-drop-rise", type=float, default=0.05,
                    help="max tolerated ABSOLUTE rise of the MoE "
                         "drop-fraction p95 (default 0.05)")
    ap.add_argument("--dcn-rise", type=float, default=0.10,
                    help="max tolerated RELATIVE rise of multislice "
                         "DCN bytes/step (default 0.10)")
    ap.add_argument("--ckpt-share-max", type=float, default=0.05,
                    help="ABSOLUTE ceiling on the checkpoint-exposed "
                         "goodput share, new side (default 0.05)")
    ap.add_argument("--slo-drop", type=float, default=0.05,
                    help="max tolerated ABSOLUTE serving SLO-attainment "
                         "drop (default 0.05)")
    args = ap.parse_args(argv)
    if len(args.files) == 2:
        old_path, new_path = args.files
    elif not args.files:
        pair = latest_rounds(args.dir)
        if pair is None:
            print("bench_gate: fewer than two BENCH_r*.json rounds in "
                  f"{args.dir!r} — nothing to gate, passing")
            return 0
        old_path, new_path = pair
    else:
        ap.error("pass exactly two files, or none for auto-discovery")
        return 2
    try:
        return gate(old_path, new_path, args.mfu_drop, args.goodput_drop,
                    args.serve_drop, args.ttft_rise, args.kernel_drop,
                    args.hbm_rise, args.accept_floor, args.moe_drop_rise,
                    args.dcn_rise, args.ckpt_share_max,
                    tile_drop=args.tile_drop,
                    attend_drop=args.attend_drop,
                    slo_drop=args.slo_drop)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_gate: cannot read inputs: {e}")
        return 2


if __name__ == "__main__":
    sys.exit(main())
