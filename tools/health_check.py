#!/usr/bin/env python
"""Health-layer self-check on the dp=8 CPU mesh (CI entry point:
``tools/run_tier1.sh --health`` / ``HEALTH_GATE=1``).

One short telemetry-enabled run with the health layer armed proves, end
to end and with zero hardware:

1. an induced-NaN fp16 step emits an ``anomaly`` event naming the FIRST
   non-finite gradient leaf and its layer (in-graph tap provenance);
2. the run closes with the terminal ``final`` marker and the report
   tool's ``health`` section validates (not truncated, flight recorder
   present and parseable, anomaly counted);
3. the health layer added ZERO host<->device sync fences on the hot
   path (the instrumented ``device_sync_count`` counter, compared
   against a telemetry-disabled twin of the same run).

Exit 0 = pass, 1 = any claim fails.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import json          # noqa: E402
import tempfile      # noqa: E402

import jax           # noqa: E402
import numpy as np   # noqa: E402

jax.config.update("jax_platforms", "cpu")


def run_once(out_dir, telemetry: bool, steps: int = 10):
    import deepspeed_tpu.utils.timer as timer_mod
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import (simple_model_params, simple_loss_fn,
                              random_batch, base_config)
    cfg = base_config(fp16={"enabled": True, "initial_scale_power": 4})
    if telemetry:
        cfg["telemetry"] = {"enabled": True, "output_path": out_dir,
                            "job_name": "health_check",
                            "report_steps": steps}
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg)
    x, y = random_batch(n=16)
    bad_x = x.copy()
    bad_x[0, 0] = np.nan
    # Warm up compiles before fencing: compile-time device traffic is
    # not hot-path traffic.
    eng.train_batch(batch=(x, y))
    eng.train_batch(batch=(x, y))
    before = timer_mod.device_sync_count()
    for i in range(steps - 3):
        eng.train_batch(batch=(x, y))
    eng.train_batch(batch=(bad_x, y))   # the induced-NaN step
    synced = timer_mod.device_sync_count() - before
    eng.telemetry.close()
    return synced


def main() -> int:
    failures = []
    with tempfile.TemporaryDirectory() as tmp_off, \
            tempfile.TemporaryDirectory() as tmp_on:
        syncs_off = run_once(tmp_off, telemetry=False)
        syncs_on = run_once(tmp_on, telemetry=True)
        if syncs_on != syncs_off:
            failures.append(
                f"fence: health-enabled run issued {syncs_on} device "
                f"syncs vs {syncs_off} disabled — hot path regressed")
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(REPO, "tools", "telemetry_report.py"))
        rep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rep)
        summary = rep.summarize(os.path.join(tmp_on,
                                             "health_check.jsonl"))
        health = summary["health"]
        if not health["available"]:
            failures.append("health section unavailable")
        if summary["truncated"] is not False:
            failures.append(
                f"truncated verdict {summary['truncated']!r} on a "
                f"cleanly closed run (final marker missing?)")
        if health["anomalies"]["nonfinite"] < 1:
            failures.append("induced-NaN step produced no non-finite "
                            "anomaly event")
        evs = health["anomalies"]["events"]
        named = [e for e in evs if e.get("first_nonfinite_leaf")]
        if not named:
            failures.append("anomaly events carry no first-non-finite-"
                            "leaf provenance")
        else:
            print(f"health_check: anomaly provenance -> leaf "
                  f"{named[0]['first_nonfinite_leaf']} (layer "
                  f"{named[0]['first_nonfinite_layer']})")
        fr = health["flight_recorder"]
        if not (fr.get("present") and fr.get("reason") == "close"
                and not fr.get("parse_error")):
            failures.append(f"flight recorder artifact wrong: {fr}")
        print(f"health_check: anomalies={health['anomalies']['counts']}, "
              f"watchdog_fires={health['watchdog_fires']}, "
              f"flight={fr.get('present')}, "
              f"added_device_syncs={syncs_on - syncs_off}")
    if failures:
        for f in failures:
            print(f"health_check FAIL: {f}")
        return 1
    print("health_check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
