#!/usr/bin/env python
"""tpu_truth.py — the one-session TPU-truth harness (ROADMAP item 1).

One command sweeps the recorded bench ladder with a jax.profiler window
armed on each rung, reconciles every capture against the analytic
roofline, and writes ``TRUTH.json`` at the repo root enumerating every
bench artifact's measurement label:

  projected       analytic number only — no run backs it.
  cpu-structural  the identical capture->ingest->reconcile pipeline ran
                  end to end on the forced-CPU host mesh; the STRUCTURE
                  (collective schedule, bucket decomposition, sync
                  discipline) is real, the absolute walls are not TPU.
  measured        a real TPU trace backs the number. This label is only
                  ever written when ``jax.default_backend() == "tpu"``
                  AND the capture ingested successfully — never on this
                  CPU box, never on a failed capture.

``tools/bench_gate.py`` ratchets these labels: once an artifact is
``measured`` it may not silently regress to ``projected`` or lose its
reconciliation section in a later round.

Exit 0 = TRUTH.json written (labels are honest by construction, even
when individual rungs fail — failures keep the prior label and record
the error). Exit 1 = could not write TRUTH.json at all.
"""
import argparse
import glob
import json
import os
import sys

RUNBOOK = """\
THE ONE-SESSION HARDWARE RUNBOOK (run these on the TPU host, in order):

  1.  git clone <repo> && cd <repo>       # no code changes needed
  2.  python tools/tpu_truth.py           # do NOT set JAX_PLATFORMS
        - autodetects the TPU backend; the same rung runners that run
          here on CPU run there on the real mesh,
        - each rung arms a 2-step jax.profiler window, ingests the
          trace from the telemetry JSONL alone, and reconciles the
          bucket decomposition against the cost-model floors,
        - labels flip projected/cpu-structural -> measured ONLY when
          the TPU trace is actually captured and ingested.
  3.  python tools/telemetry_report.py <run>/truth_<rung>.jsonl
        # optional: inspect any rung's decomposition by hand
  4.  git add TRUTH.json && git commit    # bench_gate's label ratchet
        # now holds the line: measured stays measured.

Useful knobs:
  --only RUNG     run a single rung (kernels | zero3_prefetch | moe |
                  multislice | serving_attend); others keep their
                  prior labels.
  --steps N       train/decode steps per rung (default 10; the armed
                  window is steps 4..6 regardless).
  --out PATH      write somewhere other than <repo>/TRUTH.json.
  --keep-runs DIR keep the per-rung telemetry dirs for inspection
                  instead of a temp dir.

On this CPU box the sweep is the SAME pipeline end to end — the
hardware session is a re-run, not new code.
"""

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def _tpu_present() -> bool:
    if os.environ.get("JAX_PLATFORMS", "").startswith("tpu"):
        return True
    # Probe for the accelerator DEVICE, not the libtpu package — the
    # toolchain ships libtpu on CPU-only boxes too.
    return any(os.path.exists(p) for p in
               ("/dev/accel0", "/dev/vfio/0", "/sys/class/accel/accel0"))


if not _tpu_present():
    # CPU box: force the dp=8 host mesh BEFORE jax import, same as CI.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = \
            _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

WINDOW = (4, 2)          # armed window: start_step, window_steps
DEFAULT_STEPS = 10


# ------------------------------------------------------------------ #
# Shared harness
# ------------------------------------------------------------------ #
def _summarizer():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.summarize


def _tel_cfg(out_dir: str, rung: str) -> dict:
    return {"enabled": True, "output_path": out_dir,
            "job_name": f"truth_{rung}", "report_steps": 4,
            "profile": {"start_step": WINDOW[0],
                        "window_steps": WINDOW[1]}}


def _profile_of(out_dir: str, rung: str) -> dict:
    """Profile section + registered roofline paths, from the JSONL
    alone — the same no-side-channel contract profile_check enforces."""
    summary = _summarizer()(os.path.join(out_dir, f"truth_{rung}.jsonl"))
    prof = dict(summary.get("profile") or {})
    prof["registered_paths"] = sorted(
        (summary.get("roofline") or {}).get("paths") or {})
    return prof


# ------------------------------------------------------------------ #
# Rung runners — each returns the profile section for its capture
# ------------------------------------------------------------------ #
def run_kernels(out_dir: str, steps: int) -> dict:
    """Plain dp=8 data-parallel train: the kernel-round workload."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import (base_config, random_batch, simple_loss_fn,
                              simple_model_params)
    cfg = base_config()
    cfg["telemetry"] = _tel_cfg(out_dir, "kernels")
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg)
    batch = random_batch(n=16)
    for _ in range(steps):
        eng.train_batch(batch=batch)
    eng.telemetry.close()
    return _profile_of(out_dir, "kernels")


def run_zero3(out_dir: str, steps: int) -> dict:
    """ZeRO-3 train: parameter partitioning + prefetch-overlapped
    gathers on the wire."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import (base_config, random_batch, simple_loss_fn,
                              simple_model_params)
    cfg = base_config(zero_optimization={"stage": 3})
    cfg["telemetry"] = _tel_cfg(out_dir, "zero3_prefetch")
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg)
    batch = random_batch(n=16)
    for _ in range(steps):
        eng.train_batch(batch=batch)
    eng.telemetry.close()
    return _profile_of(out_dir, "zero3_prefetch")


def run_moe(out_dir: str, steps: int) -> dict:
    """GPT2-tiny MoE (8 experts, top-2, ep=4 x dp=2): routed
    all-to-all on the wire."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2_CONFIGS, gpt2_init,
                                           gpt2_loss_fn)
    from deepspeed_tpu.moe import MoEConfig, gpt2_moe_param_shardings
    from deepspeed_tpu.parallel.topology import build_mesh

    vocab, seq = 64, 33
    moe = MoEConfig(num_experts=8, top_k=2, capacity_factor=1.5,
                    expert_parallel_size=4)
    cfg = dataclasses.replace(
        GPT2_CONFIGS["gpt2-tiny"], vocab_size=vocab, max_seq_length=seq,
        hidden_size=128, num_heads=4, num_layers=2, hidden_dropout=0.0,
        attn_dropout=0.0, dtype=jnp.float32, fused_kernels=False,
        moe=moe)
    mesh = build_mesh(ep=4)
    ds_cfg = {
        "train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "zero_optimization": {"stage": 2}, "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
        "moe": {"num_experts": moe.num_experts, "top_k": moe.top_k,
                "capacity_factor": moe.capacity_factor,
                "aux_loss_weight": moe.aux_loss_weight,
                "z_loss_weight": moe.z_loss_weight,
                "expert_parallel_size": moe.expert_parallel_size,
                "grouped_gemm": moe.grouped_gemm},
        "steps_per_print": 10 ** 9,
        "telemetry": _tel_cfg(out_dir, "moe"),
    }
    eng, *_ = deepspeed_tpu.initialize(
        model=gpt2_loss_fn(cfg, mesh=mesh),
        model_params=gpt2_init(jax.random.PRNGKey(0), cfg),
        config=ds_cfg, mesh=mesh,
        param_shardings=gpt2_moe_param_shardings(cfg))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, vocab, size=(32, seq)).astype(np.int32)
    for _ in range(steps):
        eng.train_batch(batch=tokens)
    eng.telemetry.close()
    return _profile_of(out_dir, "moe")


def run_multislice(out_dir: str, steps: int) -> dict:
    """slices=2 x dp=4 two-tier mesh: in-slice reduce-scatter vs the
    once-per-step cross-slice (DCN-tier) all-reduce."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import (base_config, random_batch, simple_loss_fn,
                              simple_model_params)
    cfg = base_config(zero_optimization={"stage": 2})
    cfg["mesh"] = {"slices": 2}
    cfg["telemetry"] = _tel_cfg(out_dir, "multislice")
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg)
    batch = random_batch(n=16)
    for _ in range(steps):
        eng.train_batch(batch=batch)
    eng.telemetry.close()
    return _profile_of(out_dir, "multislice")


def run_serving(out_dir: str, steps: int) -> dict:
    """Paged-KV serving decode: the attend path under a live window
    (profiler ticks on decode iterations)."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init

    cfg = dataclasses.replace(GPT2_CONFIGS["gpt2-tiny"],
                              dtype=jnp.float32)
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(
        cfg, params,
        config={"inference": {"max_slots": 8, "max_seq_len": 64,
                              "prefill_chunk": 8, "block_size": 16,
                              "num_blocks": 0},
                "telemetry": _tel_cfg(out_dir, "serving_attend")})
    rng = np.random.default_rng(0)
    for slot in range(4):
        prompt = rng.integers(0, cfg.vocab_size, size=12).astype(np.int32)
        eng.prefill(prompt, slot=slot)
    for _ in range(max(steps, sum(WINDOW) + 2)):
        eng.decode_once()
    eng.telemetry.close()
    return _profile_of(out_dir, "serving_attend")


# ------------------------------------------------------------------ #
# The ladder
# ------------------------------------------------------------------ #
def _latest_kernel_round() -> str:
    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r[0-9]*.json")))
    rounds = [r for r in rounds if "builder" not in r]
    return os.path.basename(rounds[-1]) if rounds else "BENCH_r07.json"


def ladder():
    return [
        ("kernels", _latest_kernel_round(), run_kernels),
        ("zero3_prefetch", "ZERO3_BENCH.json", run_zero3),
        ("moe", "MOE_BENCH.json", run_moe),
        ("multislice", "MULTISLICE_BENCH.json", run_multislice),
        ("serving_attend", "SERVE_BENCH.json", run_serving),
        # No profiled runner: these price host-side wall clock
        # (resilience goodput) or an analytic transfer tunnel
        # (offload) — a device trace does not back them either way.
        ("offload", "OFFLOAD_BENCH.json", None),
        ("resilience", "RESILIENCE_BENCH.json", None),
    ]


# ------------------------------------------------------------------ #
# Labeling
# ------------------------------------------------------------------ #
def prior_label(doc) -> str:
    """Read an artifact's legacy honesty markers: any ``projected``
    flag / ``projection`` section / PROJECTION methodology ->
    projected; an explicitly CPU-meshed measurement -> cpu-structural;
    unknown provenance defaults to projected (the cautious label)."""
    projected = []
    cpu_backed = []

    def walk(o):
        if isinstance(o, dict):
            for k, v in o.items():
                if k == "projected" and v:
                    projected.append(k)
                elif k in ("projection", "projection_zero3",
                           "projected_tpu_vm", "production_projection"):
                    projected.append(k)
                elif k == "methodology" and isinstance(v, str) and \
                        ("PROJECTION" in v or "analytic" in v.lower()):
                    projected.append(k)
                elif k == "backend" and v == "cpu":
                    cpu_backed.append(k)
                elif k in ("measured", "measured_cpu", "goodput") and v:
                    cpu_backed.append(k)
                walk(v)
        elif isinstance(o, list):
            for v in o:
                walk(v)

    walk(doc)
    if projected:
        return "projected"
    if cpu_backed:
        return "cpu-structural"
    return "projected"


def capture_ok(prof: dict) -> bool:
    if not prof.get("available") or prof.get("error"):
        return False
    wins = prof.get("windows") or []
    if not any(w.get("phase") == "stop" and w.get("ok") for w in wins):
        return False
    return bool(prof.get("n_device_ops"))


def label_for(prof: dict, backend: str, prior: str) -> str:
    if not capture_ok(prof):
        return prior                       # failed rung: never upgrade
    return "measured" if backend == "tpu" else "cpu-structural"


def _artifact_entry(rung, fname, prof, backend, prior):
    entry = {
        "ladder": rung,
        "label": prior if prof is None else label_for(prof, backend,
                                                      prior),
        "prior_label": prior,
        "backend": backend,
    }
    if prof is None:
        entry["note"] = ("rung not profiled this sweep (no runner, or "
                         "skipped via --only); label carried from the "
                         "artifact's own provenance markers")
        return entry
    if prof.get("error"):
        entry["error"] = str(prof["error"])
    wins = [w for w in (prof.get("windows") or [])
            if w.get("phase") == "stop"]
    if wins:
        entry["window"] = wins[-1]
    for k in ("per_step_wall_ms", "per_step_ms", "sum_check",
              "pallas_families_ms", "n_device_ops"):
        if prof.get(k) is not None:
            entry[k] = prof[k]
    recon = prof.get("reconciliation")
    if isinstance(recon, dict):
        entry["reconciliation"] = {
            k: recon.get(k) for k in
            ("verdict", "dominant_bucket", "predicted_bound",
             "components", "paths", "divergences")
            if recon.get(k) is not None}
    if prof.get("registered_paths"):
        entry["registered_paths"] = prof["registered_paths"]
    return entry


# ------------------------------------------------------------------ #
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_truth.py",
        description=__doc__.split("\n\n")[0],
        epilog=RUNBOOK,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--only", choices=[r for r, _, fn in ladder()
                                       if fn is not None],
                    help="run a single ladder rung; the rest keep "
                         "their prior labels")
    ap.add_argument("--steps", type=int, default=DEFAULT_STEPS,
                    help="steps per rung (armed window is steps "
                         f"{WINDOW[0]}..{WINDOW[0] + WINDOW[1]})")
    ap.add_argument("--out", default=os.path.join(REPO, "TRUTH.json"),
                    help="output path (default <repo>/TRUTH.json)")
    ap.add_argument("--keep-runs", metavar="DIR", default=None,
                    help="keep per-rung telemetry dirs here instead "
                         "of a temp dir")
    args = ap.parse_args(argv)

    backend = jax.default_backend()
    print(f"tpu_truth: backend={backend}, devices={jax.device_count()}"
          f" -> new labels are "
          f"{'measured' if backend == 'tpu' else 'cpu-structural'}")

    import tempfile
    run_root = args.keep_runs or tempfile.mkdtemp(prefix="tpu_truth_")
    os.makedirs(run_root, exist_ok=True)

    artifacts = {}
    for rung, fname, runner in ladder():
        path = os.path.join(REPO, fname)
        try:
            with open(path) as f:
                doc = json.load(f)
            prior = prior_label(doc)
        except Exception:
            prior = "projected"
        prof = None
        if runner is not None and (args.only is None
                                   or args.only == rung):
            out_dir = os.path.join(run_root, rung)
            os.makedirs(out_dir, exist_ok=True)
            try:
                prof = runner(out_dir, args.steps)
            except Exception as e:  # noqa: BLE001 — rung isolation
                prof = {"available": False,
                        "error": f"{type(e).__name__}: {e}"}
        entry = _artifact_entry(rung, fname, prof, backend, prior)
        artifacts[fname] = entry
        recon = entry.get("reconciliation") or {}
        print(f"tpu_truth: {rung:<15} {fname:<22} "
              f"{entry['prior_label']} -> {entry['label']}"
              + (f" (verdict={recon.get('verdict')}, dominant="
                 f"{recon.get('dominant_bucket')}, predicted="
                 f"{recon.get('predicted_bound')})" if recon else "")
              + (f" ERROR: {entry['error']}" if entry.get("error")
                 else ""))

    truth = {
        "generated_by": "tools/tpu_truth.py",
        "backend": backend,
        "n_devices": int(jax.device_count()),
        "window": {"start_step": WINDOW[0], "window_steps": WINDOW[1]},
        "label_policy": {
            "projected": "analytic number only; no run backs it",
            "cpu-structural": "identical capture->ingest->reconcile "
                              "pipeline ran on the forced-CPU host "
                              "mesh; structure real, walls not TPU",
            "measured": "a real TPU trace backs the number "
                        "(jax.default_backend()=='tpu' and the "
                        "capture ingested)",
        },
        "artifacts": artifacts,
    }
    try:
        with open(args.out, "w") as f:
            json.dump(truth, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError as e:
        print(f"tpu_truth: FAILED to write {args.out}: {e}")
        return 1
    n_meas = sum(1 for a in artifacts.values()
                 if a["label"] == "measured")
    print(f"tpu_truth: wrote {args.out} — {len(artifacts)} artifacts, "
          f"{n_meas} measured"
          + ("" if backend == "tpu" else
             " (labels honest for this CPU box; re-run on a TPU host "
             "to flip them — see --help)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
