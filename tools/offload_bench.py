"""One-shot recorder for the GPT-2 1.5B ZeRO-Offload bench (north-star
config). Writes OFFLOAD_BENCH.json at the repo root, which bench.py
attaches to its headline JSON line. Run detached — on the tunneled dev
chip the D2H path is ~0.03 GB/s, so a step takes minutes:

    nohup python tools/offload_bench.py > offload_bench.log 2>&1 &
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import numpy as np


def measure_tunnel():
    import jax.numpy as jnp
    x_np = np.ones((64, 1024, 1024), np.float32)  # 256 MB
    t0 = time.perf_counter()
    x = jax.device_put(x_np)
    x.block_until_ready()
    h2d = 0.25 / (time.perf_counter() - t0)
    _ = float(jnp.sum(x[0, 0, :8]))
    t0 = time.perf_counter()
    _ = jax.device_get(x)
    d2h = 0.25 / (time.perf_counter() - t0)
    del x
    return round(h2d, 3), round(d2h, 3)


def main():
    t_start = time.time()
    h2d, d2h = measure_tunnel()
    print(f"tunnel: H2D {h2d} GB/s, D2H {d2h} GB/s", flush=True)
    from bench import bench_offload_xl
    # DS_BENCH_OFFLOAD_OVERLAP / _THREADS / _BUCKET_MB select the bucketed
    # overlapped pipeline (default on); DS_BENCH_OFFLOAD_OVERLAP=0 records
    # the serial baseline for the parity comparison.
    extra = bench_offload_xl(gas=int(os.environ.get('DS_OFFLOAD_GAS', '1')),
                             n_steps=int(os.environ.get('DS_OFFLOAD_STEPS', '1')))
    extra["tunnel_h2d_gb_s"] = h2d
    extra["tunnel_d2h_gb_s"] = d2h
    extra["recorded_unix"] = int(time.time())
    extra["note"] = ("recorded one-shot on the tunneled dev chip; D2H is "
                     "the bottleneck and is an environment artifact "
                     "(TPU-VM hosts see >10 GB/s)")
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "OFFLOAD_BENCH.json")
    with open(out, "w") as f:
        json.dump(extra, f, indent=1)
    print(json.dumps(extra), flush=True)
    print(f"total {time.time()-t_start:.0f}s -> {os.path.abspath(out)}",
          flush=True)


if __name__ == "__main__":
    main()
