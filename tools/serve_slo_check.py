#!/usr/bin/env python
"""Serving-observability self-check on the dp=8 CPU mesh (CI entry
point: ``tools/run_tier1.sh --serve-slo`` / ``SERVE_SLO_GATE=1``).

One reduced shared-prefix saturation stream through two router replicas
proves, end to end and with zero hardware:

1. request tracing adds ZERO host<->device sync fences on the serving
   hot path (the instrumented ``device_sync_count`` counter, compared
   against a telemetry-disabled twin of the same stream — the trace is
   host bookkeeping by construction, and this check keeps it that way);
2. every completed request's span timeline re-validates from the JSONL
   alone: contiguous queued->prefill->decode spans (no gaps/overlaps at
   host-clock resolution), queue_wait + service_ttft == ttft;
3. each replica's serving goodput ledger is consistent (buckets sum to
   the serve wall with no double-attribution) and the report tool's
   ``serving_slo`` section parses with an SLO verdict present;
4. ``fail_on_recompile`` stays armed throughout — a post-warmup retrace
   kills the run rather than polluting the numbers.

Exit 0 = pass, 1 = any claim fails.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import json          # noqa: E402
import tempfile      # noqa: E402

import jax           # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_REQUESTS = 10
MAX_NEW = 8


def run_once(out_dir, telemetry: bool):
    """Serve the same shared-prefix stream on a 2-replica router; fence
    the measured (post-warmup) portion with device_sync_count."""
    import deepspeed_tpu.utils.timer as timer_mod
    from deepspeed_tpu.inference import (InferenceEngine, ReplicaRouter,
                                         shared_prefix_requests,
                                         synthetic_requests)
    from deepspeed_tpu.models.gpt2 import GPT2_CONFIGS, gpt2_init

    cfg = GPT2_CONFIGS["gpt2-tiny"]
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    engines = []
    for i in range(2):
        c = {"inference": {"max_slots": 8, "max_seq_len": 96,
                           "prefill_chunk": 8, "block_size": 16,
                           "spec_k": 4, "replica": f"r{i}",
                           # CPU-mesh-loose targets: the check exercises
                           # the tracker, not CPU latency.
                           "slo": {"ttft_ms": 60000.0,
                                   "tpot_ms": 60000.0}}}
        if telemetry:
            c["telemetry"] = {"enabled": True, "output_path": out_dir,
                              "job_name": f"serve_slo_r{i}",
                              "report_steps": 8,
                              "fail_on_recompile": True}
        engines.append(InferenceEngine(cfg, params, config=c))
    # Warm every compiled path before fencing: compile-time device
    # traffic is not hot-path traffic.
    warm = synthetic_requests(4, prompt_len=(4, 8), max_new_tokens=4,
                              vocab_size=cfg.vocab_size, seed=991)
    for r in warm:
        r.rid += 1000   # keep warmup traces apart from the measured ones
    ReplicaRouter(engines).serve(warm)
    for e in engines:
        e.reset_serving_stats()
    reqs = shared_prefix_requests(
        N_REQUESTS, prefix_len=24, tail_len=(4, 8),
        max_new_tokens=MAX_NEW, vocab_size=cfg.vocab_size, seed=0)
    router = ReplicaRouter(engines)
    before = timer_mod.device_sync_count()
    report = router.serve(reqs)
    synced = timer_mod.device_sync_count() - before
    for e in engines:
        e.close()
    return synced, report


def _trace_events(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "event" and \
                    rec.get("event") == "request_trace":
                out.append(rec)
    return out


def main() -> int:
    from deepspeed_tpu.monitor import validate_timeline

    failures = []
    with tempfile.TemporaryDirectory() as tmp_off, \
            tempfile.TemporaryDirectory() as tmp_on:
        syncs_off, rep_off = run_once(tmp_off, telemetry=False)
        syncs_on, rep_on = run_once(tmp_on, telemetry=True)
        if syncs_on != syncs_off:
            failures.append(
                f"fence: trace-enabled run issued {syncs_on} device "
                f"syncs vs {syncs_off} disabled — hot path regressed")
        if rep_on["unfinished"] or rep_on["recompiles"]:
            failures.append(
                f"serve: unfinished={rep_on['unfinished']}, "
                f"recompiles={rep_on['recompiles']}")
        if rep_on.get("completed") != N_REQUESTS:
            failures.append(
                f"serve: {rep_on.get('completed')} of {N_REQUESTS} "
                f"requests completed")
        # Per-replica ledgers from the live report: consistent buckets.
        for snap in rep_on.get("replicas") or []:
            led = snap.get("ledger")
            if not isinstance(led, dict):
                failures.append(
                    f"replica {snap.get('replica')}: no ledger section")
            elif not led.get("consistent"):
                failures.append(
                    f"replica {snap.get('replica')}: ledger "
                    f"double-attribution (accounted="
                    f"{led.get('accounted_fraction')})")
        # Every completed request's timeline re-validates from the
        # JSONL alone (both replicas' streams together hold them all).
        traces = []
        for i in range(2):
            traces.extend(_trace_events(
                os.path.join(tmp_on, f"serve_slo_r{i}.jsonl")))
        done = [t for t in traces if t.get("outcome") == "complete"
                and int(t.get("rid", -1)) < 1000]
        if len(done) != N_REQUESTS:
            failures.append(
                f"traces: {len(done)} completed timelines in the JSONL "
                f"streams, expected {N_REQUESTS}")
        for t in done:
            errs = validate_timeline(t)
            if errs:
                failures.append(
                    f"trace rid={t.get('rid')}: {'; '.join(errs)}")
        # The report tool's serving_slo section parses, with the ledger
        # consistent and an SLO verdict present (targets were set).
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(REPO, "tools", "telemetry_report.py"))
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        summary = trep.summarize(os.path.join(tmp_on,
                                              "serve_slo_r0.jsonl"))
        ss = summary.get("serving_slo") or {}
        if not ss.get("available"):
            failures.append("serving_slo section unavailable in the "
                            "telemetry report")
        else:
            led = ss.get("ledger") or {}
            if not led.get("consistent"):
                failures.append(f"report ledger inconsistent: {led}")
            slo = ss.get("slo")
            if not isinstance(slo, dict) or not slo.get("burn"):
                failures.append(
                    f"report slo verdict missing: {slo!r} "
                    f"({ss.get('slo_unavailable_reason')})")
            tr = ss.get("traces") or {}
            if tr.get("contiguity_violations", 1) != 0:
                failures.append(
                    f"report found {tr.get('contiguity_violations')} "
                    f"timeline contiguity violation(s)")
        srv = summary.get("serving") or {}
        if "queue_wait_ms" not in srv or "service_ttft_ms" not in srv:
            failures.append("queue_wait/service_ttft split missing "
                            "from the report's serving section")
        print(f"serve_slo_check: completed={rep_on.get('completed')}, "
              f"timelines={len(done)}, "
              f"added_device_syncs={syncs_on - syncs_off}, "
              f"slo={(ss.get('slo') or {}).get('burn')}")
    if failures:
        for f in failures:
            print(f"serve_slo_check FAIL: {f}")
        return 1
    print("serve_slo_check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
