#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim. Green = safe to ship.
# Opt-ins (same pattern, composable):
#   --bench-gate / BENCH_GATE=1 : diff the latest two bench rounds'
#       MFU/goodput via tools/bench_gate.py, fail on regression.
#   --lint / LINT_GATE=1 : run tools/ds_lint.py --check over the flagship
#       configs — fail on any unwaived finding OR stale waiver
#       (tools/lint_waivers.json is the baseline).
#   --health / HEALTH_GATE=1 : run the dp=8 health self-check
#       (tools/health_check.py): induced-NaN provenance, flight
#       recorder + final marker, zero added hot-path device syncs.
#   --serve-slo / SERVE_SLO_GATE=1 : run the dp=8 serving-observability
#       self-check (tools/serve_slo_check.py): reduced shared-prefix
#       saturation stream through two router replicas — contiguous
#       request-span timelines re-validated from the JSONL, consistent
#       per-replica goodput ledgers, a parseable serving_slo report
#       section, and zero added hot-path device syncs vs a
#       telemetry-disabled twin.
#   --profile / PROFILE_GATE=1 : run the dp=8 trace-truth self-check
#       (tools/profile_check.py): a 2-step armed jax.profiler window
#       whose trace ingests, buckets, and reconciles from the telemetry
#       JSONL alone (decomposition sums to the step wall within 5%, a
#       boundedness verdict per registered path), plus twin-run fences
#       proving zero added device syncs with profiling off AND armed
#       outside the window.
#   --resilience / RESILIENCE_GATE=1 : run the crash/kill/resume
#       harness (tools/crashkill.py run --quick: real SIGTERM/SIGKILL
#       at random steps incl. mid-write, loadable-latest probe after
#       every kill, bit-exact same-dp trajectory, floor-bounded elastic
#       trajectory) plus the goodput pricing bench (checkpoint-exposed
#       share <= 5% and steady-state goodput >= 95% at
#       snapshot_every: 50 on the dp=8 mesh -> RESILIENCE_BENCH.json).
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
for arg in "$@"; do
  case "$arg" in
    --bench-gate) BENCH_GATE=1 ;;
    --lint) LINT_GATE=1 ;;
    --health) HEALTH_GATE=1 ;;
    --serve-slo) SERVE_SLO_GATE=1 ;;
    --profile) PROFILE_GATE=1 ;;
    --resilience) RESILIENCE_GATE=1 ;;
  esac
done
if [ "${BENCH_GATE:-0}" = "1" ]; then
  python tools/bench_gate.py || rc=1
fi
if [ "${LINT_GATE:-0}" = "1" ]; then
  python tools/ds_lint.py --check || rc=1
fi
if [ "${HEALTH_GATE:-0}" = "1" ]; then
  env JAX_PLATFORMS=cpu python tools/health_check.py || rc=1
fi
if [ "${SERVE_SLO_GATE:-0}" = "1" ]; then
  env JAX_PLATFORMS=cpu python tools/serve_slo_check.py || rc=1
fi
if [ "${PROFILE_GATE:-0}" = "1" ]; then
  env JAX_PLATFORMS=cpu python tools/profile_check.py || rc=1
fi
if [ "${RESILIENCE_GATE:-0}" = "1" ]; then
  env JAX_PLATFORMS=cpu python tools/crashkill.py run --quick || rc=1
  env JAX_PLATFORMS=cpu python tools/crashkill.py bench || rc=1
fi
exit $rc
