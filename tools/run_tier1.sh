#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim. Green = safe to ship.
# Opt-in: --bench-gate (or BENCH_GATE=1) additionally diffs the latest
# two bench rounds' MFU/goodput via tools/bench_gate.py and fails on
# regression beyond threshold.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "${1:-}" = "--bench-gate" ] || [ "${BENCH_GATE:-0}" = "1" ]; then
  python tools/bench_gate.py || rc=1
fi
exit $rc
