#!/usr/bin/env python
"""Trace-truth profiling self-check on the dp=8 CPU mesh (CI entry
point: ``tools/run_tier1.sh --profile`` / ``PROFILE_GATE=1``).

One short telemetry-enabled train run with a 2-step armed
``jax.profiler`` window proves, end to end and with zero hardware:

1. the capture is located, ingested, bucketed, and reconciled FROM THE
   TELEMETRY JSONL ALONE (``profile_window`` event -> trace dir ->
   ``profile`` report section) — no side channel;
2. the per-step wall decomposition is exact: buckets + idle +
   unattributed residual sum to the measured window wall within 5%;
3. reconciliation emits a boundedness verdict for every registered
   cost-model path;
4. profiling adds ZERO host<->device sync fences when configured off
   AND when armed but outside the window (the instrumented
   ``device_sync_count`` counter vs a telemetry-disabled twin).

Exit 0 = pass, 1 = any claim fails.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import tempfile      # noqa: E402

import jax           # noqa: E402

jax.config.update("jax_platforms", "cpu")

STEPS = 12
WINDOW = (4, 2)      # start_step, window_steps
SUM_TOLERANCE = 0.05


def run_once(out_dir, telemetry: bool, profile=None, steps: int = STEPS):
    """One dp=8 train run; returns hot-path device syncs (compiles
    excluded). ``profile``: None = no profile block; (start, n) = armed
    window."""
    import deepspeed_tpu.utils.timer as timer_mod
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import (base_config, random_batch, simple_loss_fn,
                              simple_model_params)
    cfg = base_config()
    if telemetry:
        tcfg = {"enabled": True, "output_path": out_dir,
                "job_name": "profile_check", "report_steps": 5}
        if profile is not None:
            tcfg["profile"] = {"start_step": profile[0],
                               "window_steps": profile[1]}
        cfg["telemetry"] = tcfg
    eng = DeepSpeedEngine(model=simple_loss_fn,
                          model_params=simple_model_params(
                              jax.random.PRNGKey(0)),
                          config=cfg)
    batch = random_batch(n=16)
    # Warm up compiles before fencing: compile-time device traffic is
    # not hot-path traffic.
    eng.train_batch(batch=batch)
    eng.train_batch(batch=batch)
    before = timer_mod.device_sync_count()
    for _ in range(steps - 2):
        eng.train_batch(batch=batch)
    synced = timer_mod.device_sync_count() - before
    eng.telemetry.close()
    return synced


def main() -> int:
    failures = []
    with tempfile.TemporaryDirectory() as t_off, \
            tempfile.TemporaryDirectory() as t_noprof, \
            tempfile.TemporaryDirectory() as t_armed_out, \
            tempfile.TemporaryDirectory() as t_prof:
        # Fence twins: telemetry off / profile off / armed-but-outside.
        syncs_off = run_once(t_off, telemetry=False)
        syncs_noprof = run_once(t_noprof, telemetry=True, profile=None)
        syncs_armed_out = run_once(t_armed_out, telemetry=True,
                                   profile=(10 ** 6, 2))
        if syncs_noprof != syncs_off:
            failures.append(
                f"fence: profiling-off telemetry run issued "
                f"{syncs_noprof} device syncs vs {syncs_off} disabled")
        if syncs_armed_out != syncs_off:
            failures.append(
                f"fence: armed-outside-window run issued "
                f"{syncs_armed_out} device syncs vs {syncs_off} disabled")

        # The profiled run: window over 2 post-warmup hot steps.
        run_once(t_prof, telemetry=True, profile=WINDOW)

        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(REPO, "tools", "telemetry_report.py"))
        rep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rep)
        jsonl = os.path.join(t_prof, "profile_check.jsonl")
        summary = rep.summarize(jsonl)

        if summary["truncated"] is not False:
            failures.append(
                f"truncated verdict {summary['truncated']!r} on a "
                f"cleanly closed run")
        prof = summary["profile"]
        if not prof.get("available"):
            failures.append("profile section unavailable — capture not "
                            "ingested from the JSONL")
        else:
            wins = prof.get("windows") or []
            ok_stop = [w for w in wins
                       if w.get("phase") == "stop" and w.get("ok")]
            if not ok_stop:
                failures.append(f"no successful profile_window stop "
                                f"event (windows: {wins})")
            sc = prof.get("sum_check") or {}
            frac = sc.get("explained_frac")
            if frac is None or abs(frac - 1.0) > SUM_TOLERANCE:
                failures.append(
                    f"decomposition does not sum to the step wall "
                    f"within {SUM_TOLERANCE:.0%}: explained_frac={frac} "
                    f"(sum_check={sc})")
            if not prof.get("n_device_ops"):
                failures.append("ingest found zero device ops")
            recon = prof.get("reconciliation")
            if not recon:
                failures.append("no reconciliation section (cost model "
                                "missing at ingest time?)")
            else:
                if recon.get("verdict") not in ("match", "mismatch"):
                    failures.append(
                        f"boundedness verdict {recon.get('verdict')!r} "
                        f"is not decisive")
                registered = set(summary["roofline"].get("paths") or {})
                verdicts = recon.get("paths") or {}
                missing = registered - set(verdicts)
                if missing:
                    failures.append(
                        f"registered paths without a boundedness "
                        f"verdict: {sorted(missing)}")
                bad = [k for k, v in verdicts.items()
                       if v.get("verdict") not in
                       ("match", "mismatch", "indeterminate",
                        "unavailable")]
                if bad:
                    failures.append(f"malformed path verdicts: {bad}")
            if not failures:
                print(f"profile_check: per-step "
                      f"wall={prof['per_step_wall_ms']}ms, buckets="
                      f"{prof['per_step_ms']}, explained="
                      f"{sc.get('explained_frac'):.1%}, verdict="
                      f"{recon['verdict']} (dominant="
                      f"{recon['dominant_bucket']}, predicted="
                      f"{recon['predicted_bound']}), "
                      f"paths={list((recon.get('paths') or {}))}, "
                      f"added_syncs off/outside="
                      f"{syncs_noprof - syncs_off}/"
                      f"{syncs_armed_out - syncs_off}")
    if failures:
        for f in failures:
            print(f"profile_check FAIL: {f}")
        return 1
    print("profile_check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
