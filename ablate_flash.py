"""Dev tool: sweep flash-attention block sizes on the bench train step.

The causal flash kernel skips (q,k) blocks entirely above the diagonal, so
smaller blocks skip more of the masked upper triangle (ceiling: 50% of
attention FLOPs) at the cost of more per-grid-step overhead. This times the
full bench step (chunked-CE, dots remat) per block target to find the best
trade. Usage: python ablate_flash.py [model] [mbs] [blocks...]
"""
import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2 import gpt2_flops_per_token, gpt2_init, gpt2_loss_fn
import deepspeed_tpu.ops.flash_attention as fa

MODEL = sys.argv[1] if len(sys.argv) > 1 else "gpt2-large"
MBS = int(sys.argv[2]) if len(sys.argv) > 2 else 4
BLOCKS = [int(b) for b in sys.argv[3:]] or [1024, 512, 256]

cfg = dataclasses.replace(GPT2_CONFIGS[MODEL], max_seq_length=1024,
                          remat_policy="dots", hidden_dropout=0.0,
                          attn_dropout=0.0, scan_layers=False)
S = cfg.max_seq_length
loss_fn = gpt2_loss_fn(cfg)
tx = optax.adamw(1e-4)


def cast(p):
    return jax.tree_util.tree_map(
        lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a, p)


def run(block):
    fa._BLOCK_TARGET = block
    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    opt_state = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cast(p), batch, rng))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batch = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                          size=(MBS, S + 1), dtype=np.int32))
    rng = jax.random.PRNGKey(1)
    params, opt_state, loss = step(params, opt_state, batch, rng)
    _ = float(loss)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, batch, rng)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / n
    tf = MBS * S / dt * gpt2_flops_per_token(cfg, S) / 1e12
    print(f"block={block:5d}: {dt*1000:7.1f} ms/step  {tf:6.1f} TFLOPs "
          f"({tf/197.0*100:.1f}% v5e peak)", flush=True)
    del params, opt_state


for b in BLOCKS:
    run(b)
