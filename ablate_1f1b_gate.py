"""Measure the 1F1B tick-gating win (VERDICT r4 'what's weak' #2).

Before round 5 the 1F1B tick ran embed+stage+head+vjp on EVERY stage every
tick, masked off-stage — for GPT-2 the head is the 50k-vocab projection,
the most expensive op in the model. Round 5 gates each sub-tick behind a
``lax.cond`` whose predicate is tick-uniform (per-RANK predicates deadlock
the collective rendezvous once dp/mp partitioning puts collectives inside
one rank's branch — see the spmd_1f1b.py docstring), skipping the
warmup/drain windows outright. This script times gated vs ungated on the
virtual 8-device CPU mesh. NOTE: CPU devices share host cores, so this
measurement also counts the off-stage parallel work that a real TPU pod
runs latency-free — it is an upper bound on the per-tick FLOPs saved, and
a lower bound proof that the gates engage.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python ablate_1f1b_gate.py
"""
import dataclasses
import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from deepspeed_tpu.models import GPT2_CONFIGS
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipe_spec
from deepspeed_tpu.parallel.topology import build_mesh
from deepspeed_tpu.runtime.pipe.spmd_1f1b import spmd_pipeline_1f1b_grads

PP, M = 4, 8
# Head-heavy shape: big vocab vs small hidden, so the off-stage head waste
# dominates exactly the way GPT-2's 50k vocab does at scale.
cfg = dataclasses.replace(
    GPT2_CONFIGS["gpt2-tiny"], vocab_size=8192, hidden_size=128,
    num_layers=PP, num_heads=4, max_seq_length=128,
    hidden_dropout=0.0, attn_dropout=0.0)


def timed(gfn, spec, batch, mesh, n=10):
    with jax.set_mesh(mesh):
        f = jax.jit(gfn)
        loss, grads = f(spec.params, batch, jax.random.PRNGKey(2))
        jax.block_until_ready((loss, grads))
        t0 = time.perf_counter()
        for _ in range(n):
            loss, grads = f(spec.params, batch, jax.random.PRNGKey(2))
        jax.block_until_ready((loss, grads))
        return (time.perf_counter() - t0) / n, float(loss)


def main():
    mesh = build_mesh(pp=PP, dp=2)
    spec = gpt2_pipe_spec(cfg, rng=jax.random.PRNGKey(0))
    batch = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(M * 2, 128), dtype=np.int32)

    results = {}
    for name, gate in (("ungated", False), ("gated", True)):
        gfn = spmd_pipeline_1f1b_grads(
            spec.embed_fn, spec.stage_fn, spec.head_fn, num_stages=PP,
            num_micro_batches=M, mesh=mesh, gate_offstage=gate)
        dt, loss = timed(gfn, spec, batch, mesh)
        results[name] = {"step_ms": dt * 1e3, "loss": loss}
        print(f"{name:8s}: {dt*1e3:8.1f} ms/step  loss={loss:.4f}")

    assert abs(results["gated"]["loss"] - results["ungated"]["loss"]) < 1e-4
    speedup = results["ungated"]["step_ms"] / results["gated"]["step_ms"]
    print(json.dumps({
        "ablation": "1f1b_offstage_gating", "pp": PP, "micro": M,
        "vocab": cfg.vocab_size,
        "ungated_ms": round(results["ungated"]["step_ms"], 1),
        "gated_ms": round(results["gated"]["step_ms"], 1),
        "speedup": round(speedup, 2)}))


if __name__ == "__main__":
    main()
